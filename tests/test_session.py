"""Decode session: feature composition on one scheduler loop (ISSUE 18).

The session refactor's acceptance contract, pinned:

  * COMPOSITION PARITY — greedy queued output is bit-identical across
    every legal feature combination: plain, radix prefix cache, spec
    decode, spec UNDER radix, chunked prefill on/off (× radix). The
    existing per-feature parity suites (test_paged_cache, test_serving,
    test_speculative, test_envs) now run THROUGH the session — `generate`
    has no non-session queued path — so this file pins only the
    combinations that used to be illegal.
  * DISPATCH A/B — on an overlapping corpus, spec+radix combined issues
    STRICTLY fewer dispatch events (admission launches + decode/verify
    chunk iterations) than either feature alone, and strictly fewer
    prefill tokens than spec alone. Events, not tokens, is the honest
    combined-vs-radix metric: a verify step dispatches k+1 tokens where
    plain decode dispatches 1, trading tokens-per-launch for fewer
    launches (docs/DECODE_ANALYSIS.md §dispatch accounting).
  * DRAFTER SEEDING — satellite (b): admissions seed the n-gram drafter
    from the radix tree's cached continuation of the matched prefix
    (radix.extend_text / matched_continuation), so repeat prompts accept
    drafts from the first generated token instead of cold-starting.
  * ONE CODE PATH — serving/engine.py owns no decode loop: its chunk fn
    IS the session's, and a gateway-shaped per-row stream equals the
    rollout scheduler's greedy stream for the same prompt.
  * compose_check — the single legality matrix: what still raises, and
    that everything else constructs.

CI runs this file as the `session-parity` tier-1 step under
NANORLHF_LOCK_CHECK=1.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nanorlhf_tpu.core import ModelConfig, init_params
from nanorlhf_tpu.sampler import SamplingParams, compose_check, generate
from nanorlhf_tpu.serving.radix import RadixCache, prompt_key

EOS, PAD = 3, 0
TP = 12          # padded prompt width
MT = 8           # max_tokens


@pytest.fixture(scope="module")
def tiny():
    config = ModelConfig.qwen2_tiny(vocab_size=128)
    params = init_params(config, jax.random.PRNGKey(7), jnp.float32)
    return config, params


def _left_pad(rows, T, pad=PAD):
    ids = np.full((len(rows), T), pad, np.int32)
    for i, r in enumerate(rows):
        ids[i, T - len(r):] = r
    ids = jnp.asarray(ids)
    return ids, ids != pad


# one 8-real-token family repeated: maximal prefix overlap, so radix
# full-hits every repeat and (after the first release extends the tree
# with the generated text) the drafter seed covers the whole greedy
# continuation of rows 3..6
FAMILY = [5, 6, 7, 8, 9, 10, 11, 12]
OVERLAP = [FAMILY] * 6


def _run(tiny, *, spec_k=0, radix=False, prefill_chunk=0, greedy=True,
         prompts=OVERLAP, key=0):
    config, params = tiny
    ids, mask = _left_pad(prompts, TP)
    sp = SamplingParams(max_tokens=MT, greedy=greedy, page_size=4,
                        decode_rows=2, spec_k=spec_k,
                        prefill_chunk=prefill_chunk,
                        temperature=1.0, top_p=0.9)
    stats, spec_stats = [], []
    out = generate(params, config, ids, mask, jax.random.PRNGKey(key),
                   sp, eos_token_id=EOS, pad_token_id=PAD,
                   paged_stats_out=stats, spec_stats_out=spec_stats,
                   prefix_cache=RadixCache() if radix else None)
    return np.asarray(out), stats[0], (spec_stats[0] if spec_stats
                                       else None)


@pytest.fixture(scope="module")
def ab(tiny):
    """The four corners of the spec×radix square, one call each."""
    runs = {}
    runs["plain"] = _run(tiny)
    runs["radix"] = _run(tiny, radix=True)
    runs["spec"] = _run(tiny, spec_k=3)
    runs["both"] = _run(tiny, spec_k=3, radix=True)
    return runs


def test_spec_under_radix_three_way_bit_parity(ab):
    """Greedy output identical across plain / radix / spec / spec+radix:
    the composition that raised ValueError before the session exists and
    changes dispatch shape ONLY."""
    ref = ab["plain"][0]
    for name in ("radix", "spec", "both"):
        np.testing.assert_array_equal(
            ref, ab[name][0], err_msg=f"{name} diverged from plain")


def test_combined_strictly_fewer_dispatch_events(ab):
    """THE perf gate: spec+radix < min(each alone) in dispatch EVENTS on
    the overlapping corpus — the radix hit removes prefill iterations
    and the SEEDED drafter removes decode iterations that unseeded spec
    cannot (the continuation lives in the tree, not in the repeat row's
    own prompt). Also: combined moves strictly fewer prefill tokens than
    spec alone (the radix half of the win, token-denominated)."""
    ev = {k: v[1]["dispatch_events"] for k, v in ab.items()}
    assert ev["both"] < min(ev["radix"], ev["spec"]), ev
    assert (ab["both"][1]["prefill_token_dispatch"]
            < ab["spec"][1]["prefill_token_dispatch"])
    # the mechanism, not just the outcome: the seed window is armed and
    # seeded acceptance strictly beats unseeded on this corpus
    feats = ab["both"][1]["session"]["features"]
    assert feats["spec_k"] == 3 and feats["prefix_cache"]
    assert feats["drafter_seed_window"] > 0
    acc_both = int(np.asarray(ab["both"][2]["accepted"]))
    acc_spec = int(np.asarray(ab["spec"][2]["accepted"]))
    assert acc_both > acc_spec, (acc_both, acc_spec)


@pytest.mark.parametrize("radix", [False, True],
                         ids=["cold-pool", "radix"])
def test_chunked_prefill_bit_identical(tiny, radix):
    """prefill_chunk on/off: greedy streams bit-identical (the final
    chunk runs the same bucketed suffix forward and samples from the
    same admission fold), with the chunked run actually chunking —
    backlog observed, admissions split."""
    out0, st0, _ = _run(tiny, radix=radix)
    out1, st1, _ = _run(tiny, radix=radix, prefill_chunk=4)
    np.testing.assert_array_equal(out0, out1)
    assert st0["chunked_admissions"] == 0
    assert st1["chunked_admissions"] > 0
    assert st1["prefill_backlog_peak"] > 0
    # chunking must not change WHAT ran, only when: same decode output,
    # same rows admitted
    assert st1["admitted_midloop"] >= st0["admitted_midloop"]


def test_session_stats_surface(ab):
    """The /statusz `session` section the trainer re-exports: mode,
    per-row flags, counters — shaped for tools/inspect_run.py."""
    s = ab["both"][1]["session"]
    assert s["mode"] == "rollout"
    assert s["rows"] == 2 and len(s["row_flags"]) == 2
    assert s["counters"]["dispatch_events"] == (
        s["counters"]["launches"] + s["counters"]["decode_iterations"])
    assert s["pending_prefill"] == {"rows": [], "backlog_tokens": 0}


# --------------------------------------------------------------------- #
# drafter seeding primitives (satellite b)
# --------------------------------------------------------------------- #

def test_radix_text_extension_and_continuation():
    rc = RadixCache()
    rc.reset(num_pages=16, page_size=4)
    toks = np.asarray(FAMILY, np.int32)
    row = np.full(TP, PAD, np.int32)
    row[TP - len(toks):] = toks
    mask = row != PAD
    key = prompt_key(row, mask)
    plan = rc.plan(key, pad_count=TP - len(toks), n_blocks=5,
                   prompt_len=TP)
    rc.insert(key, plan.row_pages, TP)
    # nothing generated yet: the continuation of the full prompt is empty
    assert rc.matched_continuation(key, 8).size == 0
    gen = [40, 41, 42, 43]
    rc.extend_text(key + tuple(t * 2 + 1 for t in gen))
    np.testing.assert_array_equal(rc.matched_continuation(key, 8), gen)
    # window truncates from the front of the continuation
    np.testing.assert_array_equal(rc.matched_continuation(key, 2),
                                  gen[:2])
    # an unknown prompt has no continuation
    other = prompt_key(np.roll(row, 1), mask)
    assert rc.matched_continuation(other, 8).size == 0
    # text-only leaves hold no pages: releasing the one holder frees the
    # whole pool (the extension can never leak a page)
    rc.release(plan.row_pages.copy())
    rc.reset(num_pages=16, page_size=4)
    assert rc.pool.free_count == 16


# --------------------------------------------------------------------- #
# one scheduler code path: serving == rollout (tentpole composition 3)
# --------------------------------------------------------------------- #

def test_engine_has_no_private_decode_loop():
    import nanorlhf_tpu.sampler.paged.scheduler as sched
    import nanorlhf_tpu.sampler.paged.session as session
    import nanorlhf_tpu.serving.engine as engine

    # the engine's pre-session loop primitives are GONE, not just unused
    for name in ("_engine_chunk", "_engine_decode_body", "_engine_install",
                 "_ENGINE_STATIC"):
        assert not hasattr(engine, name), name
    # the rollout scheduler drives the session's chunk fns, not copies
    assert sched._decode_chunk is session._decode_chunk
    assert sched._spec_chunk is session._spec_chunk
    assert sched.DecodeSession is session.DecodeSession


def test_gateway_stream_equals_rollout_stream(tiny):
    """Same prompt, same greedy params: the engine's per-request stream
    and the rollout scheduler's row are the same token sequence — the
    pin that serving and rollout share one scheduler code path."""
    from nanorlhf_tpu.sampler.paged.session import DecodeSession
    from nanorlhf_tpu.serving.engine import ServingEngine

    config, params = tiny
    rollout, _, _ = _run(tiny, prompts=[FAMILY])
    row = rollout[0]
    eos = np.nonzero(row == EOS)[0]
    want = row[:int(eos[0]) + 1] if eos.size else row

    eng = ServingEngine(params, config, eos_token_id=EOS,
                        pad_token_id=PAD, page_size=4, prompt_len=TP,
                        max_new_tokens=MT, rows=2, seed=0)
    try:
        assert isinstance(eng.session, DecodeSession)
        req, reason = eng.submit(FAMILY, greedy=True)
        assert reason is None
        got = np.asarray(list(eng.stream(req)), np.int32)
        snap = eng.snapshot()
    finally:
        eng.close()
    np.testing.assert_array_equal(got, want)
    sess = snap["session"]
    assert sess["mode"] == "serving"
    assert sess["features"]["per_row_sampling"]
    assert len(sess["row_flags"]) == eng.rows


def test_engine_chunked_prefill_stream_identical(tiny):
    """Engine with prefill_chunk: the long cold prompt's stream is
    bit-identical to the unchunked engine (first token rides _deliver
    instead of the admission return), and the session counted the
    chunked admission."""
    from nanorlhf_tpu.serving.engine import ServingEngine

    config, params = tiny

    def serve(prefill_chunk):
        eng = ServingEngine(params, config, eos_token_id=EOS,
                            pad_token_id=PAD, page_size=4, prompt_len=TP,
                            max_new_tokens=MT, rows=2, seed=0,
                            prefill_chunk=prefill_chunk)
        try:
            req, reason = eng.submit(FAMILY, greedy=True)
            assert reason is None
            toks = list(eng.stream(req))
            snap = eng.snapshot()
        finally:
            eng.close()
        return toks, snap

    t0, s0 = serve(0)
    t1, s1 = serve(4)
    assert t0 == t1
    assert s0["session"]["counters"]["chunked_admissions"] == 0
    assert s1["session"]["counters"]["chunked_admissions"] == 1
    assert s1["counters"]["completed"] == 1


def test_engine_spec_greedy_stream_identical(tiny):
    """Engine with spec_k: greedy streams match the non-spec engine
    bit-for-bit (verify accepts the argmax chain), and non-greedy /
    short-budget submits are rejected up front — the verify rule
    compiles against static sampling params."""
    from nanorlhf_tpu.serving.engine import ServingEngine

    config, params = tiny

    def serve(spec_k):
        eng = ServingEngine(params, config, eos_token_id=EOS,
                            pad_token_id=PAD, page_size=4, prompt_len=TP,
                            max_new_tokens=MT, rows=2, seed=0,
                            spec_k=spec_k)
        try:
            if spec_k:
                with pytest.raises(ValueError, match="greedy"):
                    eng.submit(FAMILY, greedy=False)
                with pytest.raises(ValueError, match="greedy"):
                    eng.submit(FAMILY, greedy=True, max_tokens=2)
            req, reason = eng.submit(FAMILY, greedy=True)
            assert reason is None
            return list(eng.stream(req))
        finally:
            eng.close()

    assert serve(0) == serve(3)


# --------------------------------------------------------------------- #
# compose_check: the one legality matrix
# --------------------------------------------------------------------- #

ILLEGAL = [
    (dict(page_size=4, compaction_segments=2), False, "page_size"),
    (dict(spec_k=2, compaction_segments=2), False, "spec_k"),
    (dict(), True, "continuous batching"),
    (dict(page_size=4), True, "continuous batching"),
    (dict(prefill_chunk=4), False, "prefill_chunk"),
    (dict(page_size=4, prefill_chunk=4), False, "prefill_chunk"),
]

LEGAL = [
    dict(page_size=4, decode_rows=2, spec_k=3),
    dict(page_size=4, decode_rows=2, prefill_chunk=4, spec_k=3),
    dict(page_size=4, spec_k=3),
    dict(compaction_segments=2),
]


@pytest.mark.parametrize("kw,pc,match", ILLEGAL)
def test_compose_check_illegal(kw, pc, match):
    with pytest.raises(ValueError, match=match):
        compose_check(SamplingParams(**kw), prefix_cache=pc)


@pytest.mark.parametrize("kw", LEGAL)
def test_compose_check_legal(kw):
    compose_check(SamplingParams(**kw), prefix_cache=(
        kw.get("page_size", 0) > 0 and kw.get("decode_rows", 0) > 0))
