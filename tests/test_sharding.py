"""Mesh + GSPMD sharding on the virtual 8-device CPU mesh.

This is the distributed test story the reference lacks (SURVEY.md §4):
exercise pjit sharding and the implied collectives without TPU hardware.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from nanorlhf_tpu.core import ModelConfig, init_params, padded_forward_logits
from nanorlhf_tpu.core.lora import LoraConfig, init_lora_params
from nanorlhf_tpu.parallel import (
    MeshConfig,
    make_mesh,
    param_sharding_rules,
    shard_params,
    batch_sharding,
)


def test_mesh_resolution():
    assert MeshConfig(-1, 2, 2).resolve(8) == (2, 2, 2, 1)
    assert MeshConfig(8, 1, 1).resolve(8) == (8, 1, 1, 1)
    with pytest.raises(ValueError):
        MeshConfig(3, 2, 2).resolve(8)


def test_eight_devices_available():
    assert len(jax.devices()) == 8, "conftest must force 8 fake CPU devices"


def test_rules_cover_all_leaves():
    config = ModelConfig.qwen2_tiny()
    params = init_params(config, jax.random.PRNGKey(0), jnp.float32)
    params["lora"] = init_lora_params(config, LoraConfig(r=4), jax.random.PRNGKey(1), jnp.float32)
    rules = param_sharding_rules(params)
    flat_p = jax.tree_util.tree_leaves_with_path(params)
    flat_r = jax.tree.leaves(rules, is_leaf=lambda x: isinstance(x, P))
    assert len(flat_p) == len(flat_r)
    for (path, leaf), spec in zip(flat_p, flat_r):
        assert len(spec) <= leaf.ndim, f"{path}: spec {spec} vs shape {leaf.shape}"


@pytest.mark.parametrize("mesh_shape", [(8, 1, 1), (2, 2, 2), (1, 4, 2)])
def test_sharded_forward_matches_unsharded(mesh_shape):
    config = ModelConfig.qwen2_tiny(vocab_size=128)
    params = init_params(config, jax.random.PRNGKey(0), jnp.float32)
    ids = np.random.default_rng(0).integers(2, 128, (8, 10)).astype(np.int32)
    ids[:, :2] = 0  # some padding
    want = np.asarray(padded_forward_logits(params, config, jnp.asarray(ids), 0))

    mesh = make_mesh(MeshConfig(*mesh_shape))
    sharded = shard_params(params, mesh)
    batch = jax.device_put(jnp.asarray(ids), batch_sharding(mesh))

    fwd = jax.jit(lambda p, b: padded_forward_logits(p, config, b, 0))
    got = np.asarray(fwd(sharded, batch))
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_sharded_params_memory_is_distributed():
    """fsdp/tensor axes actually split the big kernels across devices."""
    config = ModelConfig.qwen2_tiny(vocab_size=128)
    params = init_params(config, jax.random.PRNGKey(0), jnp.float32)
    mesh = make_mesh(MeshConfig(1, 4, 2))
    sharded = shard_params(params, mesh)
    kernel = sharded["layers"]["gate_proj"]["kernel"]  # [L, D, F] P(None,fsdp,tensor)
    shard_shapes = {s.data.shape for s in kernel.addressable_shards}
    L, D, F = kernel.shape
    assert shard_shapes == {(L, D // 4, F // 2)}


def test_dcn_mesh_axis():
    """Multi-slice data axis (MeshConfig.dcn_data): the data axis spans
    dcn_data x per-slice groups with slices slowest-varying, so a gradient
    psum over 'data' is the only collective that would cross DCN. On the
    CPU mesh the 8 virtual devices partition into contiguous groups (no
    slice_index attr) — axis semantics identical."""
    cfg = MeshConfig(data=-1, fsdp=2, dcn_data=2)
    assert cfg.resolve(8) == (4, 2, 1, 1)
    mesh = make_mesh(cfg)
    assert mesh.shape == {"data": 4, "fsdp": 2, "tensor": 1, "sp": 1}
    # slice-major: first half of the data axis = first device group
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    assert set(ids[:2].flatten().tolist()) == set(range(4))
    # a batch-sharded matmul still runs (collectives compile + execute)
    x = jax.device_put(jnp.ones((8, 16)), batch_sharding(mesh))
    w = jax.device_put(jnp.ones((16, 4)),
                       NamedSharding(mesh, P(None, "tensor")))
    y = jax.jit(lambda x, w: x @ w)(x, w)
    np.testing.assert_allclose(np.asarray(y), 16.0)
    with pytest.raises(ValueError):
        MeshConfig(data=3, dcn_data=2).resolve(3)


def test_rules_shard_large_geometries_evenly():
    """TP/FSDP claims hold at real scale: every sharded dim of the 7B/8B
    trees divides by its mesh axis on a (1,2,2) mesh. eval_shape only —
    no 7B allocation."""
    from nanorlhf_tpu.core import init_params

    mesh = make_mesh(MeshConfig(1, 2, 2, 1), devices=jax.devices()[:4])
    for cfg in (ModelConfig.qwen2_7b(), ModelConfig.llama3_8b(),
                ModelConfig.qwen2_0_5b()):
        shapes = jax.eval_shape(
            lambda k, c=cfg: init_params(c, k, jnp.bfloat16),
            jax.random.PRNGKey(0),
        )
        rules = param_sharding_rules(shapes)
        leaves = jax.tree_util.tree_leaves_with_path(shapes)
        specs = jax.tree_util.tree_leaves_with_path(rules)
        assert len(leaves) == len(specs)
        for (path, leaf), (_, spec) in zip(leaves, specs):
            for dim, axes in enumerate(spec):
                if axes is None:
                    continue
                axes = axes if isinstance(axes, tuple) else (axes,)
                n = 1
                for a in axes:
                    n *= mesh.shape[a]
                assert leaf.shape[dim] % n == 0, (
                    f"{cfg.hidden_size=} {path} dim {dim} "
                    f"({leaf.shape[dim]}) not divisible by {axes} ({n})"
                )


def test_update_minibatch_no_involuntary_remat(tmp_path, capfd):
    """The [mini] -> [micro, grad_accum] stack keeps the SHARDED row dim
    major and constrains it ONCE outside the scan, so GSPMD reaches the
    per-microbatch sharding without the "Involuntary full
    rematerialization" fallback (replicate-then-repartition of a minibatch
    tensor EVERY optimizer step — VERDICT r3 #2, visible in the
    MULTICHIP_r03 dryrun tail). The warning reproduces on the dryrun's SP
    dense-GRPO phase — mesh (data=4, sp=2) — where the scan-body
    constraint's dim-0 data sharding collides with the SP shard_map's
    dim-1 sequence sharding (mutation-verified: reverting the trainer
    layout makes this test fail). Compile must stay fallback-free."""
    import zlib

    from nanorlhf_tpu.data import ToyTokenizer, load_prompt_dataset
    from nanorlhf_tpu.trainer import AlgoName, RLConfig, RLTrainer

    tok = ToyTokenizer(256)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=256)
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.bfloat16)
    dataset = load_prompt_dataset("synthetic:64", tok, max_prompt_len=12)
    cfg = RLConfig(
        algo=AlgoName.GRPO,
        output_dir=str(tmp_path / "remat"),
        response_length=8,
        temperature=1.0,
        sample_n=2,
        per_device_train_batch_size=4,
        gradient_accumulation_steps=1,
        num_mini_batches=1,
        total_episodes=16,  # one update: pd(4) x data(4)
        use_lora=True, lora_r=4, lora_alpha=8,
        gradient_checkpointing=True,
        save_steps=0,
        report_to="none",
    )
    mesh = make_mesh(MeshConfig(4, 1, 1, 2), devices=jax.devices())

    def reward(pmt_and_responses, eos_token):
        return np.asarray(
            [(zlib.crc32(s.encode()) % 17) / 17.0 for s in pmt_and_responses],
            np.float32,
        )

    # the persistent compile cache (conftest) can serve the update
    # executable without compiling — and the warning only fires DURING
    # compilation, which would make this assertion vacuous. Force fresh
    # compiles for this test only.
    saved = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    try:
        trainer = RLTrainer(cfg, mcfg, tok, params, dataset, reward, mesh=mesh)
        trainer.train(num_updates=1)
    finally:
        jax.config.update("jax_enable_compilation_cache", saved)
    err = capfd.readouterr().err
    assert "Involuntary full rematerialization" not in err, err[-2000:]
