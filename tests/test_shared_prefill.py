"""Shared-prompt-KV prefill (SamplingParams.shared_prompt_prefill).

The n>1 fanout must be a pure optimization: prefilling each prompt once and
fanning the KV/first-logits out to its N samples has to reproduce the
repeat-×N path's token streams EXACTLY (same [B*N] shapes and the same
fold_in key stream reach the categorical either way). Reference capability:
vLLM's prefix sharing for `SamplingParams(n=4)` requests
(`/root/reference/GRPO/grpo_trainer.py:127`).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanorlhf_tpu.core import ModelConfig, init_params
from nanorlhf_tpu.sampler import SamplingParams, generate

EOS, PAD = 3, 0


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig.qwen2_tiny(vocab_size=128)
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    return cfg, params


def _prompts():
    # varied left-padding: per-row prompt_len must fan out correctly
    ids = jnp.asarray([
        [PAD, PAD, 5, 6],
        [PAD, 7, 8, 9],
        [10, 11, 12, 13],
        [PAD, PAD, PAD, 14],
    ], jnp.int32)
    return ids, (ids != PAD)


def _gen(model, shared, **kw):
    cfg, params = model
    ids, mask = _prompts()
    sp = SamplingParams(n=4, max_tokens=10, shared_prompt_prefill=shared, **kw)
    return generate(params, cfg, ids, mask, jax.random.PRNGKey(42), sp,
                    eos_token_id=EOS, pad_token_id=PAD)


def test_tokens_match_repeat_path(model):
    a = _gen(model, True)
    b = _gen(model, False)
    assert a.shape == b.shape == (16, 10)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_siblings_diverge(model):
    """Fanout must NOT collapse the N samples of a prompt onto one stream —
    checked PER PROMPT (a per-shard fanout bug could collapse some prompts
    while others escape)."""
    out = np.asarray(_gen(model, True))
    rows = out.reshape(4, 4, -1)
    # at temperature 1 / top_p .95 over an untrained model, every prompt
    # should have at least one divergent sibling pair
    for p in range(4):
        assert any(
            not np.array_equal(rows[p, i], rows[p, j])
            for i in range(4) for j in range(i + 1, 4)
        ), f"prompt {p}: all 4 siblings emitted identical streams"


def test_capture_logprobs_match(model):
    ta, la = _gen(model, True, capture_logprobs=True)
    tb, lb = _gen(model, False, capture_logprobs=True)
    np.testing.assert_array_equal(np.asarray(ta), np.asarray(tb))
    # the two paths are different compiled programs; XLA fusion choices move
    # the f32 logsumexp by a few ulp even though every sampled token matches
    np.testing.assert_allclose(np.asarray(la), np.asarray(lb), atol=1e-5)


def test_exact_nucleus_path(model):
    a = _gen(model, True, top_k=0)
    b = _gen(model, False, top_k=0)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_greedy_fanout(model):
    """Greedy n>1: all siblings must emit the prompt's argmax stream."""
    out = np.asarray(_gen(model, True, greedy=True))
    ref = np.asarray(_gen(model, False, greedy=True))
    np.testing.assert_array_equal(out, ref)
    rows = out.reshape(4, 4, -1)
    for p in range(4):
        for j in range(1, 4):
            np.testing.assert_array_equal(rows[p, 0], rows[p, j])


def test_compaction_path(model):
    """Segmented/compacting decode accepts the fanout (same distribution;
    identical streams BEFORE the first compaction, so a segment width the
    batch never compacts under reproduces the monolithic tokens)."""
    a = _gen(model, True, compaction_segments=2)
    b = _gen(model, False, compaction_segments=2)
    assert a.shape == b.shape == (16, 10)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_n1_unaffected(model):
    cfg, params = model
    ids, mask = _prompts()
    kw = dict(eos_token_id=EOS, pad_token_id=PAD)
    a = generate(params, cfg, ids, mask, jax.random.PRNGKey(1),
                 SamplingParams(n=1, max_tokens=8, shared_prompt_prefill=True),
                 **kw)
    b = generate(params, cfg, ids, mask, jax.random.PRNGKey(1),
                 SamplingParams(n=1, max_tokens=8, shared_prompt_prefill=False),
                 **kw)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
