"""Sequence-parallel full-model forward == single-device forward."""

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from nanorlhf_tpu.core import ModelConfig, init_params, model_forward
from nanorlhf_tpu.core.lora import LoraConfig, init_lora_params
from nanorlhf_tpu.parallel.sp import sp_forward_logits


def _mesh():
    return Mesh(np.asarray(jax.devices()), ("sp",))


def _inputs(rng, B=2, T=32, vocab=128, pad=0):
    ids = rng.integers(2, vocab, size=(B, T)).astype(np.int32)
    ids[0, :5] = pad  # left padding on one row
    mask = (ids != pad).astype(np.int32)
    pos = np.cumsum(mask, axis=1) - mask
    return jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(pos)


def test_sp_forward_matches_single_device(rng):
    config = ModelConfig.qwen2_tiny(vocab_size=128)
    params = init_params(config, jax.random.PRNGKey(0), jnp.float32)
    ids, mask, pos = _inputs(rng)
    want = np.asarray(model_forward(params, config, jnp.where(mask.astype(bool), ids, 0),
                                    mask, pos))
    got = np.asarray(sp_forward_logits(params, config, ids, mask, pos, _mesh()))
    real = np.asarray(mask)[:, :, None]
    np.testing.assert_allclose(got * real, want * real, rtol=2e-3, atol=2e-3)


def test_sp_forward_with_lora(rng):
    config = ModelConfig.qwen2_tiny(vocab_size=128)
    params = init_params(config, jax.random.PRNGKey(0), jnp.float32)
    lora_cfg = LoraConfig(r=4, alpha=8)
    lora = init_lora_params(config, lora_cfg, jax.random.PRNGKey(1), jnp.float32)
    lora = jax.tree.map(
        lambda x: x + 0.02 * jax.random.normal(jax.random.PRNGKey(2), x.shape, x.dtype),
        lora,
    )
    full = {**params, "lora": lora}
    ids, mask, pos = _inputs(rng)
    want = np.asarray(model_forward(full, config, jnp.where(mask.astype(bool), ids, 0),
                                    mask, pos, lora_scale=lora_cfg.scale))
    got = np.asarray(sp_forward_logits(full, config, ids, mask, pos, _mesh(),
                                       lora_scale=lora_cfg.scale))
    real = np.asarray(mask)[:, :, None]
    np.testing.assert_allclose(got * real, want * real, rtol=2e-3, atol=2e-3)


def test_sp_forward_gradients_flow(rng):
    """SP training viability: grads through ring attention + scan match the
    single-device forward's grads."""
    config = ModelConfig.qwen2_tiny(vocab_size=64)
    params = init_params(config, jax.random.PRNGKey(0), jnp.float32)
    ids, mask, pos = _inputs(rng, B=1, T=16, vocab=64)
    mesh = _mesh()

    def loss_sp(p):
        lg = sp_forward_logits(p, config, ids, mask, pos, mesh)
        return jnp.sum((lg * mask[:, :, None]) ** 2)

    def loss_ref(p):
        lg = model_forward(p, config, jnp.where(mask.astype(bool), ids, 0), mask, pos)
        return jnp.sum((lg * mask[:, :, None]) ** 2)

    g_sp = jax.grad(loss_sp)(params)
    g_ref = jax.grad(loss_ref)(params)
    for a, b in zip(jax.tree.leaves(g_sp), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-3)
