"""SP × FSDP forward: params sharded at rest, per-layer gather, vs reference."""

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from nanorlhf_tpu.core import ModelConfig, init_params, model_forward
from nanorlhf_tpu.core.lora import LoraConfig, init_lora_params
from nanorlhf_tpu.parallel.sp import sp_fsdp_forward_logits


def _mesh():
    return Mesh(np.asarray(jax.devices()).reshape(2, 4), ("fsdp", "sp"))


def _inputs(rng, B=2, T=16, vocab=128, pad=0):
    ids = rng.integers(2, vocab, size=(B, T)).astype(np.int32)
    ids[0, :3] = pad
    mask = (ids != pad).astype(np.int32)
    pos = np.cumsum(mask, axis=1) - mask
    return jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(pos)


def test_sp_fsdp_matches_single_device(rng):
    config = ModelConfig.qwen2_tiny(vocab_size=128)
    params = init_params(config, jax.random.PRNGKey(0), jnp.float32)
    ids, mask, pos = _inputs(rng)
    want = np.asarray(model_forward(params, config,
                                    jnp.where(mask.astype(bool), ids, 0), mask, pos))
    got = np.asarray(sp_fsdp_forward_logits(params, config, ids, mask, pos, _mesh()))
    real = np.asarray(mask)[:, :, None]
    np.testing.assert_allclose(got * real, want * real, rtol=2e-3, atol=2e-3)


def test_sp_fsdp_with_lora(rng):
    config = ModelConfig.qwen2_tiny(vocab_size=128)
    params = init_params(config, jax.random.PRNGKey(0), jnp.float32)
    lcfg = LoraConfig(r=4, alpha=8)
    lora = init_lora_params(config, lcfg, jax.random.PRNGKey(1), jnp.float32)
    lora = jax.tree.map(
        lambda x: x + 0.02 * jax.random.normal(jax.random.PRNGKey(2), x.shape, x.dtype),
        lora,
    )
    full = {**params, "lora": lora}
    ids, mask, pos = _inputs(rng)
    want = np.asarray(model_forward(full, config,
                                    jnp.where(mask.astype(bool), ids, 0), mask, pos,
                                    lora_scale=lcfg.scale))
    got = np.asarray(sp_fsdp_forward_logits(full, config, ids, mask, pos, _mesh(),
                                            lora_scale=lcfg.scale))
    real = np.asarray(mask)[:, :, None]
    np.testing.assert_allclose(got * real, want * real, rtol=2e-3, atol=2e-3)


def test_sp_fsdp_untied_lm_head(rng):
    """The lazy lm_head gather path (untied embeddings)."""
    import dataclasses

    config = dataclasses.replace(ModelConfig.qwen2_tiny(vocab_size=128),
                                 tie_word_embeddings=False)
    params = init_params(config, jax.random.PRNGKey(3), jnp.float32)
    ids, mask, pos = _inputs(rng)
    want = np.asarray(model_forward(params, config,
                                    jnp.where(mask.astype(bool), ids, 0), mask, pos))
    got = np.asarray(sp_fsdp_forward_logits(params, config, ids, mask, pos, _mesh()))
    real = np.asarray(mask)[:, :, None]
    np.testing.assert_allclose(got * real, want * real, rtol=2e-3, atol=2e-3)


def test_sp_fsdp_gradients_sharded_like_params(rng):
    """Grads flow through the per-layer all_gathers (transpose =
    reduce-scatter) and match the single-device grads."""
    config = ModelConfig.qwen2_tiny(vocab_size=64)
    params = init_params(config, jax.random.PRNGKey(0), jnp.float32)
    ids, mask, pos = _inputs(rng, B=1, T=8, vocab=64)
    mesh = _mesh()

    def loss_sp(p):
        lg = sp_fsdp_forward_logits(p, config, ids, mask, pos, mesh)
        return jnp.sum((lg * mask[:, :, None]) ** 2)

    def loss_ref(p):
        lg = model_forward(p, config, jnp.where(mask.astype(bool), ids, 0), mask, pos)
        return jnp.sum((lg * mask[:, :, None]) ** 2)

    g_sp = jax.grad(loss_sp)(params)
    g_ref = jax.grad(loss_ref)(params)
    for a, b in zip(jax.tree.leaves(g_sp), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-3)


def test_sp_fsdp_flash_ring_grad_matches_xla_ring(rng):
    """fsdp×sp with the FLASH ring differentiated (attn_impl="pallas"
    routes `_sp_fsdp_forward_local`'s attention through
    `ring_attention_flash`, whose custom_vjp backward re-runs the ring
    with the global lse): gradients of the scored logprobs must match the
    einsum ("xla") ring's autodiff — the SP update path's kernel choice
    must not change the update direction."""
    from nanorlhf_tpu.parallel.sp import sp_score_logprobs

    config = ModelConfig.qwen2_tiny(vocab_size=128)
    params = init_params(config, jax.random.PRNGKey(0), jnp.float32)
    ids_j, _, _ = _inputs(rng)
    mesh = _mesh()

    def loss(p, impl):
        lp = sp_score_logprobs(
            p, config, ids_j, 0, 1.0, mesh, fsdp_axis="fsdp",
            attn_impl=impl,
        )
        return (lp * (ids_j != 0)).sum()

    g_xla = jax.jit(jax.grad(lambda p: loss(p, "xla")))(params)
    g_flash = jax.jit(jax.grad(lambda p: loss(p, "pallas")))(params)
    for a, b in zip(jax.tree.leaves(g_xla), jax.tree.leaves(g_flash)):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=2e-4, atol=2e-4
        )
