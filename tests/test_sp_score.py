"""SP logprob scoring == single-device logprobs (labels cross shards)."""

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from nanorlhf_tpu.core import ModelConfig, init_params, padded_forward_logits
from nanorlhf_tpu.ops.masking import logprobs_from_logits
from nanorlhf_tpu.parallel.sp import sp_score_logprobs


def _reference_lp(params, config, qr, pad, temperature):
    logits = padded_forward_logits(params, config, qr, pad)
    labels = jnp.concatenate([qr[:, 1:], jnp.zeros_like(qr[:, :1])], axis=1)
    lp = logprobs_from_logits(logits, labels, temperature)
    return lp.at[:, -1].set(0.0)


def test_sp_score_matches_single_device(rng):
    config = ModelConfig.qwen2_tiny(vocab_size=128)
    params = init_params(config, jax.random.PRNGKey(0), jnp.float32)
    ids = rng.integers(2, 128, size=(2, 32)).astype(np.int32)
    ids[0, :4] = 0  # left padding
    qr = jnp.asarray(ids)
    mesh = Mesh(np.asarray(jax.devices()), ("sp",))
    got = np.asarray(sp_score_logprobs(params, config, qr, 0, 0.9, mesh))
    want = np.asarray(_reference_lp(params, config, qr, 0, 0.9))
    real = np.asarray(qr != 0)
    np.testing.assert_allclose(got * real, want * real, rtol=2e-3, atol=2e-3)


def test_sp_score_fsdp_variant(rng):
    config = ModelConfig.qwen2_tiny(vocab_size=128)
    params = init_params(config, jax.random.PRNGKey(0), jnp.float32)
    qr = jnp.asarray(rng.integers(2, 128, size=(1, 16)).astype(np.int32))
    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("fsdp", "sp"))
    got = np.asarray(sp_score_logprobs(params, config, qr, 0, 1.0, mesh,
                                       fsdp_axis="fsdp"))
    want = np.asarray(_reference_lp(params, config, qr, 0, 1.0))
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_sp_score_response_slice_semantics(rng):
    """Slicing [ctx-1:T-1] reproduces the trainer's response logprobs."""
    config = ModelConfig.qwen2_tiny(vocab_size=128)
    params = init_params(config, jax.random.PRNGKey(0), jnp.float32)
    ctx, T = 8, 24
    qr = jnp.asarray(rng.integers(2, 128, size=(2, T)).astype(np.int32))
    mesh = Mesh(np.asarray(jax.devices()), ("sp",))
    lp = sp_score_logprobs(params, config, qr, 0, 1.0, mesh)
    got = np.asarray(lp[:, ctx - 1 : T - 1])
    # single-device trainer path
    want = np.asarray(logprobs_from_logits(
        padded_forward_logits(params, config, qr, 0,
                              response_context_length=ctx),
        qr[:, ctx:], 1.0,
    ))
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_sp_score_flash_ring_matches(rng):
    """attn_impl="pallas" routes the scorer through the forward-only flash
    ring (interpret mode here) — logprobs must match the xla einsum ring."""
    config = ModelConfig.qwen2_tiny(vocab_size=128)
    params = init_params(config, jax.random.PRNGKey(0), jnp.float32)
    ids = rng.integers(2, 128, size=(2, 64)).astype(np.int32)
    ids[0, :6] = 0  # left padding
    qr = jnp.asarray(ids)
    mesh = Mesh(np.asarray(jax.devices()[:2]), ("sp",))
    got = np.asarray(sp_score_logprobs(params, config, qr, 0, 0.9, mesh,
                                       attn_impl="pallas"))
    want = np.asarray(sp_score_logprobs(params, config, qr, 0, 0.9, mesh))
    real = np.asarray(qr != 0)
    np.testing.assert_allclose(got * real, want * real, rtol=2e-4, atol=2e-4)


def test_sp_score_values_matches_score_forward(rng):
    """sp_score_values (PPO value head at ring scale): plain sp mesh AND the
    fsdp-sharded head="score" branch, values + gradients vs score_forward."""
    from nanorlhf_tpu.core.model import score_forward
    from nanorlhf_tpu.parallel.sp import sp_score_values

    config = ModelConfig.qwen2_tiny(vocab_size=128)
    params = init_params(config, jax.random.PRNGKey(0), jnp.float32)
    params = {k: v for k, v in params.items() if k != "lm_head"}
    params["score"] = jax.random.normal(
        jax.random.PRNGKey(5), (config.hidden_size, 1), jnp.float32
    ) * 0.1
    ids = rng.integers(2, 128, size=(2, 32)).astype(np.int32)
    ids[1, :5] = 0
    qr = jnp.asarray(ids)
    want = np.asarray(score_forward(params, config, qr, 0))

    for mesh in (Mesh(np.asarray(jax.devices()[:2]), ("sp",)),
                 Mesh(np.asarray(jax.devices()[:4]).reshape(2, 2),
                      ("fsdp", "sp"))):
        fsdp = "fsdp" if "fsdp" in mesh.shape else None
        got = np.asarray(sp_score_values(params, config, qr, 0, mesh,
                                         fsdp_axis=fsdp))
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

        def loss_sp(p):
            return (sp_score_values(p, config, qr, 0, mesh,
                                    fsdp_axis=fsdp) ** 2).mean()

        def loss_ref(p):
            return (score_forward(p, config, qr, 0) ** 2).mean()

        g_sp = jax.jit(jax.grad(loss_sp))(params)["score"]
        g_ref = jax.grad(loss_ref)(params)["score"]
        np.testing.assert_allclose(np.asarray(g_sp), np.asarray(g_ref),
                                   rtol=2e-4, atol=2e-5)
