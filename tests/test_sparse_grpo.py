"""Sparse GRPO end-to-end on the CPU mesh: r1 reward protocol, sparse filter,
bucketed logprob/update, accuracy eval hook."""

import json

import numpy as np

import jax
import jax.numpy as jnp

from nanorlhf_tpu.core import ModelConfig, init_params
from nanorlhf_tpu.data import ToyTokenizer
from nanorlhf_tpu.entrypoints.grpo_r1 import (
    build_prompt_dataset,
    make_accuracy_func,
    make_r1_reward,
    synthetic_math_corpus,
)
from nanorlhf_tpu.parallel import MeshConfig
from nanorlhf_tpu.trainer import AlgoName, RLConfig
from nanorlhf_tpu.trainer.sparse_grpo import SparseGRPOTrainer


def test_sparse_grpo_end_to_end(tmp_path):
    tok = ToyTokenizer(512)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=512)
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)

    train_qa = synthetic_math_corpus(64)
    eval_qa = synthetic_math_corpus(8, seed=1)
    dataset = build_prompt_dataset(train_qa, tok, max_prompt_len=16)

    cfg = RLConfig(
        algo=AlgoName.GRPO,
        output_dir=str(tmp_path / "r1"),
        response_length=8,
        temperature=1.0,
        sample_n=2,
        kl_coef=0.0,
        total_episodes=64,   # batch = 1*2*2*8 devices = 32 → 2 updates
        per_device_train_batch_size=1,
        gradient_accumulation_steps=2,
        num_mini_batches=2,
        learning_rate=1e-4,
        use_lora=True, lora_r=4, lora_alpha=8,
        gradient_checkpointing=False,
        mesh=MeshConfig(-1, 1, 1),
        eval_steps=2,
        save_steps=2,
    )

    # random model never answers correctly -> all-zero rewards -> z-scores 0
    # -> everything sparse-filtered. Force variance with a random reward so
    # the bucketed update path actually runs.
    rng = np.random.default_rng(0)

    def noisy_reward(pmt_and_responses, responses_ids, tokenizer):
        return rng.random(len(pmt_and_responses)).astype(np.float32)

    trainer = SparseGRPOTrainer(
        cfg, mcfg, tok, params, dataset, noisy_reward,
        accuracy_func=make_accuracy_func(eval_qa, max_prompt_len=16,
                                         eval_response_length=4,
                                         use_subprocess=False),
    )
    state = trainer.train()
    assert state["global_step"] == 2

    lines = [json.loads(l) for l in open(tmp_path / "r1" / "metrics.jsonl")]
    assert "initial_accuracy" in lines[0]
    step_lines = [l for l in lines if "sparse/kept_frac" in l]
    assert step_lines and all(np.isfinite(l["loss/policy_avg_new"]) for l in step_lines)
    assert any("eval_accuracy_new" in l for l in step_lines)


def test_sparse_grpo_all_zero_rewards_skips_update(tmp_path):
    """Binary reward that is always 0 -> every group filtered -> no crash."""
    tok = ToyTokenizer(512)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=512)
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    train_qa = synthetic_math_corpus(32)
    dataset = build_prompt_dataset(train_qa, tok, max_prompt_len=16)
    cfg = RLConfig(
        algo=AlgoName.GRPO, output_dir=str(tmp_path / "r0"), response_length=4,
        temperature=1.0, sample_n=2, total_episodes=8,
        per_device_train_batch_size=1, gradient_accumulation_steps=1,
        num_mini_batches=1, use_lora=True, lora_r=4, lora_alpha=8,
        gradient_checkpointing=False, mesh=MeshConfig(-1, 1, 1), save_steps=0,
    )
    reward = make_r1_reward(dict(train_qa), use_subprocess=False)
    cfg.report_to = "jsonl"
    trainer = SparseGRPOTrainer(cfg, mcfg, tok, params, dataset, reward)
    state = trainer.train()  # all updates skipped, but loop completes
    assert state["episode"] == 8
    # skipped updates still leave a metrics row recording the raw score
    # (distinguishes starved-at-zero from starved-solved regimes)
    skip_rows = [json.loads(l)
                 for l in open(tmp_path / "r0" / "metrics.jsonl")
                 if "sparse_skip/raw_score_mean" in l]
    assert len(skip_rows) == state["rollouts"] - state["global_step"] > 0
    assert all(r["sparse_skip/raw_score_mean"] == 0.0 for r in skip_rows)
    # event rows must NOT carry 'episode' (the step-row discriminator) and
    # must be uniquely indexed (TB x-axis across consecutive skips)
    assert all("episode" not in r for r in skip_rows)
    steps = [r["step"] for r in skip_rows]
    assert len(set(steps)) == len(steps)


def test_sparse_grpo_sampler_capture(tmp_path):
    """Capture path in the sparse trainer: policy scoring skipped, drift
    metric emitted, update trains."""
    tok = ToyTokenizer(512)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=512)
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    dataset = build_prompt_dataset(synthetic_math_corpus(32), tok, max_prompt_len=16)
    cfg = RLConfig(
        algo=AlgoName.GRPO, output_dir=str(tmp_path / "cap"), response_length=8,
        temperature=1.0, sample_n=2, total_episodes=16,
        per_device_train_batch_size=1, gradient_accumulation_steps=1,
        num_mini_batches=1, use_lora=True, lora_r=4, lora_alpha=8,
        gradient_checkpointing=False, mesh=MeshConfig(-1, 1, 1), save_steps=0,
    )
    cfg.sampler_logprob_capture = True
    rng = np.random.default_rng(0)

    def noisy_reward(pmt_and_responses, responses_ids, tokenizer):
        return rng.random(len(pmt_and_responses)).astype(np.float32)

    trainer = SparseGRPOTrainer(cfg, mcfg, tok, params, dataset, noisy_reward)
    trainer.train(num_updates=1)
    lines = [json.loads(l) for l in open(tmp_path / "cap" / "metrics.jsonl")
             if "sparse/kept_frac" in l]
    m = lines[-1]
    assert "sampler_capture/ratio_drift_new" in m
    assert m["sampler_capture/ratio_drift_new"] < 1e-2
