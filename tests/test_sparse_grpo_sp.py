"""SparseGRPO with sequence parallelism: the sp>1 mesh path must train and
match single-device numerics (VERDICT r1 #3 — SP as a trainer capability)."""

import json
import zlib

import numpy as np

import jax
import jax.numpy as jnp

from nanorlhf_tpu.core import ModelConfig, init_params
from nanorlhf_tpu.data import ToyTokenizer
from nanorlhf_tpu.entrypoints.grpo_r1 import (
    build_prompt_dataset,
    synthetic_math_corpus,
)
from nanorlhf_tpu.parallel import MeshConfig, make_mesh
from nanorlhf_tpu.trainer import AlgoName, RLConfig
from nanorlhf_tpu.trainer.sparse_grpo import SparseGRPOTrainer


def det_reward(pmt_and_responses, responses_ids, tokenizer):
    """Deterministic pseudo-random reward (crc32, not `hash` — PYTHONHASHSEED
    must not leak into the equivalence check)."""
    return np.asarray(
        [(zlib.crc32(s.encode()) % 17) / 17.0 for s in pmt_and_responses],
        np.float32,
    )


def _make_trainer(tmp_path, name, mesh):
    tok = ToyTokenizer(512)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=512)
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    dataset = build_prompt_dataset(synthetic_math_corpus(32), tok, max_prompt_len=16)
    cfg = RLConfig(
        algo=AlgoName.GRPO,
        output_dir=str(tmp_path / name),
        response_length=8,
        temperature=1.0,
        sample_n=2,
        kl_coef=0.05,
        total_episodes=4,    # world=1 -> batch 2 -> 2 updates
        per_device_train_batch_size=2,
        gradient_accumulation_steps=1,
        num_mini_batches=1,
        learning_rate=1e-3,
        use_lora=True, lora_r=4, lora_alpha=8,
        gradient_checkpointing=False,
        save_steps=0,
        eval_steps=0,
    )
    return SparseGRPOTrainer(cfg, mcfg, tok, params, dataset, det_reward,
                             mesh=mesh)


def _lora_leaves(trainer):
    return [np.asarray(x) for x in jax.tree.leaves(trainer.params["lora"])]


def test_sp2_matches_single_device(tmp_path):
    devs = jax.devices()
    ctrl = _make_trainer(
        tmp_path, "ctrl", make_mesh(MeshConfig(1, 1, 1, 1), devices=devs[:1])
    )
    sp = _make_trainer(
        tmp_path, "sp2", make_mesh(MeshConfig(1, 1, 1, 2), devices=devs[:2])
    )
    assert sp._sp_on() and not ctrl._sp_on()
    s1 = ctrl.train()
    s2 = sp.train()
    assert s1["global_step"] == s2["global_step"] == 2

    # same PRNG stream + same deterministic reward -> identical rollouts;
    # ring attention only reorders f32 reductions, so trained params must
    # agree to bf16 resolution (LoRA adapters are stored bf16 -> one ulp of
    # slack at |x|~0.5 is 2e-3)
    for a, b in zip(_lora_leaves(ctrl), _lora_leaves(sp)):
        np.testing.assert_allclose(
            a.astype(np.float32), b.astype(np.float32), rtol=5e-3, atol=2e-3
        )

    m1 = [json.loads(l) for l in open(tmp_path / "ctrl" / "metrics.jsonl")
          if "sparse/kept_frac" in l]
    m2 = [json.loads(l) for l in open(tmp_path / "sp2" / "metrics.jsonl")
          if "sparse/kept_frac" in l]
    for a, b in zip(m1, m2):
        assert abs(a["loss/policy_avg_new"] - b["loss/policy_avg_new"]) < 1e-3
        assert abs(a["objective/kl_rollout_old"] - b["objective/kl_rollout_old"]) < 1e-3


def test_sp_with_fsdp_trains(tmp_path):
    """sp=2 x fsdp=2: params sharded at rest, gathered per layer inside the
    SP forward — one update runs and stays finite."""
    devs = jax.devices()
    tr = _make_trainer(
        tmp_path, "spfsdp", make_mesh(MeshConfig(1, 2, 1, 2), devices=devs[:4])
    )
    assert tr._sp_on() and tr._fsdp_axis() == "fsdp"
    tr.train(num_updates=1)
    m = [json.loads(l) for l in open(tmp_path / "spfsdp" / "metrics.jsonl")
         if "sparse/kept_frac" in l]
    assert m and np.isfinite(m[-1]["loss/policy_avg_new"])
