"""Speculative rollout decode (sampler/speculative.py).

Pins the ISSUE-5 acceptance contract: greedy spec streams bit-identical to
the monolithic loop on the CPU mesh, rejection sampling distribution-exact
(small-vocab enumeration), per-row cache-length/key_mask consistency after
mixed accept lengths, capture_logprobs parity, EOS inside an accepted
draft, the compaction guard, and the k-query verify kernel vs its oracle.

The deterministic oracle is the "cycle model": tied embeddings off, every
layer zeroed, orthogonal embedding rows, and lm_head wired so the logits
after token t are a one-hot on sigma(t) — the model is an exact Markov
chain over single tokens (context-free), so greedy streams, acceptance
lengths, and EOS positions are all constructible by hand, and a cyclic
sigma makes output maximally self-repetitive (the drafter's best case).
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nanorlhf_tpu.core import ModelConfig, init_params
from nanorlhf_tpu.sampler import SamplingParams, generate

EOS, PAD = 3, 0


@pytest.fixture(scope="module")
def tiny():
    config = ModelConfig.qwen2_tiny(vocab_size=128)
    params = init_params(config, jax.random.PRNGKey(7), jnp.float32)
    return config, params


def cycle_model(sigma, vocab=16, peak=12.0):
    """(config, params) for the deterministic Markov model: after token t
    the logits are `peak`·onehot(sigma[t]) (attention/MLP zeroed, so
    context beyond the current token is ignored)."""
    cfg = dataclasses.replace(
        ModelConfig.qwen2_tiny(vocab_size=vocab), tie_word_embeddings=False
    )
    params = init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    D = cfg.hidden_size
    z = jax.tree.map(jnp.zeros_like, params["layers"])
    # keep the layernorm gains at 1 (zeroing them is fine too — projections
    # are zero — but ones keep the residual stream well-conditioned)
    z["input_layernorm"] = jnp.ones_like(params["layers"]["input_layernorm"])
    z["post_attention_layernorm"] = jnp.ones_like(
        params["layers"]["post_attention_layernorm"]
    )
    params["layers"] = z
    embed = jnp.zeros((vocab, D), jnp.float32).at[
        jnp.arange(vocab), jnp.arange(vocab)
    ].set(1.0)
    params["embed_tokens"] = embed
    # final rms_norm maps embed[t] -> sqrt(D)·e_t (one nonzero dim), so
    # lm_head[t, v] = (peak/sqrt(D))·[v == sigma(t)] gives the one-hot row
    sig = jnp.asarray(sigma, jnp.int32)
    head = jnp.zeros((vocab, vocab), jnp.float32).at[
        jnp.arange(vocab), sig
    ].set(peak / np.sqrt(D))
    params["lm_head"] = head.astype(jnp.float32)[:D, :] if D < vocab else \
        jnp.zeros((D, vocab), jnp.float32).at[:vocab, :].set(head)
    return cfg, params


def _left_pad(rows, T, pad=PAD):
    ids = np.full((len(rows), T), pad, np.int32)
    for i, r in enumerate(rows):
        ids[i, T - len(r):] = r
    ids = jnp.asarray(ids)
    return ids, ids != pad


def _gen(model, key=0, spec_k=0, max_tokens=24, prompts=None, **kw):
    cfg, params = model
    ids, mask = prompts if prompts is not None else _left_pad(
        [[5, 6, 7, 8], [PAD, 9, 10], [11, 12, 13, 14]], 5
    )
    stats = []
    sp = SamplingParams(max_tokens=max_tokens, spec_k=spec_k, **kw)
    out = generate(params, cfg, ids, mask, jax.random.PRNGKey(key), sp,
                   eos_token_id=EOS, pad_token_id=PAD, spec_stats_out=stats)
    return out, (stats[0] if stats else None)


def _stat(stats, name):
    return int(np.asarray(stats[name]))


# --------------------------------------------------------------------- #
# greedy bit-parity with the monolithic loop
# --------------------------------------------------------------------- #

def test_greedy_spec_bit_identical(tiny):
    mono, _ = _gen(tiny, greedy=True)
    for k in (1, 2, 4):
        spec, stats = _gen(tiny, greedy=True, spec_k=k)
        np.testing.assert_array_equal(np.asarray(mono), np.asarray(spec))
        # worst case (acceptance ~0) still emits >= 1 token per verify step
        assert _stat(stats, "emitted") >= _stat(stats, "verify_steps")


def test_greedy_spec_capture_logprobs_parity(tiny):
    (mt, mlp), _ = _gen(tiny, greedy=True, capture_logprobs=True)
    (st, slp), _ = _gen(tiny, greedy=True, capture_logprobs=True, spec_k=4)
    np.testing.assert_array_equal(np.asarray(mt), np.asarray(st))
    # verify logits == decode_step logits bit-for-bit on CPU, but the two
    # compiled programs may fuse the logsumexp differently — ulp tolerance
    np.testing.assert_allclose(np.asarray(mlp), np.asarray(slp), atol=1e-5)


def test_greedy_spec_with_fanout(tiny):
    prompts = _left_pad([[5, 6, 7], [9, 10, 11]], 4)
    mono, _ = _gen(tiny, greedy=True, n=2, prompts=prompts)
    spec, _ = _gen(tiny, greedy=True, n=2, spec_k=3, prompts=prompts)
    assert spec.shape == (4, 24)
    np.testing.assert_array_equal(np.asarray(mono), np.asarray(spec))


def test_greedy_spec_int8_kv_cache(tiny):
    cfg, params = tiny
    q_model = (dataclasses.replace(cfg, kv_cache_quant="int8"), params)
    mono, _ = _gen(q_model, greedy=True)
    spec, _ = _gen(q_model, greedy=True, spec_k=4)
    np.testing.assert_array_equal(np.asarray(mono), np.asarray(spec))


# --------------------------------------------------------------------- #
# repetitive corpus: the drafter must actually pay off
# --------------------------------------------------------------------- #

def test_repetitive_cycle_accepts_and_halves_dispatches():
    """A 4-cycle Markov model emits a period-4 stream; once the n-gram
    matcher warms up, every draft is accepted and verify dispatches drop
    to ~max_tokens/(k+1) — the bench's >=2x criterion, pinned here."""
    sigma = list(range(16))
    sigma[5], sigma[6], sigma[7], sigma[8] = 6, 7, 8, 5   # 5->6->7->8->5
    model = cycle_model(sigma)
    prompts = _left_pad([[5, 6, 7, 8, 5]], 6)
    mono, _ = _gen(model, greedy=True, max_tokens=48, prompts=prompts)
    spec, stats = _gen(model, greedy=True, max_tokens=48, spec_k=4,
                       prompts=prompts)
    np.testing.assert_array_equal(np.asarray(mono), np.asarray(spec))
    assert np.asarray(mono)[0, :8].tolist() == [6, 7, 8, 5, 6, 7, 8, 5]
    steps = _stat(stats, "verify_steps")
    assert steps * 2 <= 48, f"{steps} verify steps for 48 tokens"
    acc = _stat(stats, "accepted") / max(_stat(stats, "drafted"), 1)
    assert acc > 0.5


def test_eos_inside_accepted_draft_terminates_row():
    """The prompt seeds an n-gram whose continuation runs THROUGH EOS: the
    draft [3(EOS), 11, ...] is accepted up to the EOS and the row must
    stop there — emission truncated at the EOS, tail stays PAD, and the
    stream still matches the monolithic loop bit-for-bit."""
    sigma = list(range(16))
    sigma[5], sigma[6], sigma[7] = 6, 7, EOS   # 5->6->7->EOS
    sigma[EOS] = 11                            # continuation past EOS exists
    model = cycle_model(sigma)
    # buffer contains "6 7 3 9" so context [6, 7] drafts [3, 9, ...]
    prompts = _left_pad([[9, 6, 7, EOS, 9, 5, 6]], 8)
    mono, _ = _gen(model, greedy=True, max_tokens=16, prompts=prompts)
    spec, stats = _gen(model, greedy=True, max_tokens=16, spec_k=3,
                       spec_ngram=2, prompts=prompts)
    row = np.asarray(spec)[0]
    assert row[:2].tolist() == [7, EOS]        # prefill 7, then EOS accepted
    assert (row[2:] == PAD).all()
    np.testing.assert_array_equal(np.asarray(mono), np.asarray(spec))


def test_mixed_accept_lengths_key_mask_consistency():
    """Rows accepting at different rates: after every iteration the carry
    must hold, per row, a CONTIGUOUS key_mask [Tp-plen, Tp+n_gen-1) (the
    last emitted token's slot stays unmasked until its KV is written) and
    out rows padded past n_gen — the bookkeeping the per-row carry
    refactor exists for."""
    from nanorlhf_tpu.sampler.sampler import _prefill_state
    from nanorlhf_tpu.sampler.speculative import (
        _draft_fn, _spec_state, _verify_fn,
    )

    sigma = list(range(16))
    sigma[5], sigma[6], sigma[7], sigma[8] = 6, 7, 8, 5   # cycle row
    cfg, params = cycle_model(sigma)
    # row 0 cycles (high acceptance); row 1 walks the identity (sigma[t]=t
    # -> constant stream, accepted too); row 2 has a fresh context with no
    # match (zero acceptance at first)
    ids, mask = _left_pad([[5, 6, 7, 8, 5], [9, 9, 9], [1, 2, 4, 10, 12]], 6)
    Tp, max_tokens, k = ids.shape[1], 20, 3
    base = _prefill_state(
        params, cfg, ids, mask, jax.random.PRNGKey(0),
        max_tokens=max_tokens, eos_token_id=EOS, pad_token_id=PAD,
        temperature=1.0, top_p=0.95, greedy=True, lora_scale=1.0, top_k=64,
        capture_logprobs=False, approx_top_k=True, cache_extra=k,
    )
    state = _spec_state(base)
    statics = dict(Tp=Tp, max_tokens=max_tokens, eos_token_id=EOS,
                   pad_token_id=PAD, spec_k=k, temperature=1.0, top_p=0.95,
                   greedy=True, lora_scale=1.0, top_k=64,
                   capture_logprobs=False, approx_top_k=True)
    plen = np.asarray(jnp.sum(mask, axis=1))
    accept_rates = []
    for _ in range(4):
        drafts = _draft_fn(ids, state, Tp=Tp, spec_k=k, spec_ngram=2,
                           pad_token_id=PAD)
        prev_gen = np.asarray(state[7])
        state = _verify_fn(params, cfg, state, drafts, **statics)
        key_mask = np.asarray(state[4])
        n_gen = np.asarray(state[7])
        out = np.asarray(state[1])
        accept_rates.append(n_gen - prev_gen)
        for b in range(3):
            want = np.zeros(key_mask.shape[1], bool)
            want[Tp - plen[b]: Tp + n_gen[b] - 1] = True
            np.testing.assert_array_equal(
                key_mask[b], want, err_msg=f"row {b} key_mask"
            )
            assert (out[b, n_gen[b]:] == PAD).all()
    rates = np.stack(accept_rates)                 # [iters, rows]
    assert rates.max() > 1, "no row ever accepted a draft"
    # rows genuinely advanced at different rates at least once
    assert any(len(set(r.tolist())) > 1 for r in rates)


def test_sampled_spec_capture_matches_scoring_pass(tiny):
    """Sampled spec with capture: the verify-logit logprobs must equal a
    full rescoring forward at every emitted position — the strongest pin
    on per-row cache/key_mask bookkeeping under MIXED accept lengths (a
    corrupted cache slot would shift some position's distribution and the
    rescore would disagree)."""
    from nanorlhf_tpu.core import padded_forward_logits
    from nanorlhf_tpu.ops.masking import logprobs_from_logits

    cfg, params = tiny
    ids, mask = _left_pad([[5, 6, 7], [9, 10, 11, 12]], 5)
    T, temp = 10, 0.9
    (out, lp), _ = _gen(tiny, key=11, spec_k=3, max_tokens=T,
                        prompts=(ids, mask), temperature=temp,
                        capture_logprobs=True)
    out, lp = np.asarray(out), np.asarray(lp)
    qr = np.concatenate([np.asarray(ids), out], axis=1)
    logits = padded_forward_logits(params, cfg, jnp.asarray(qr), PAD,
                                   response_context_length=ids.shape[1])
    scored = np.asarray(logprobs_from_logits(logits, jnp.asarray(out), temp))
    for b in range(out.shape[0]):
        for t in range(T):
            if out[b, t] == PAD:
                break
            assert abs(lp[b, t] - scored[b, t]) < 1e-3, (b, t)
            if out[b, t] == EOS:
                break


# --------------------------------------------------------------------- #
# sampled rows: distribution exactness
# --------------------------------------------------------------------- #

def test_rejection_sampling_exact_small_vocab_enumeration():
    """Exact enumeration of the acceptance rule's induced marginal: with a
    deterministic (point-mass) drafter, P(token = d) = p(d) and
    P(token = v != d) = (1 - p(d)) · p(v)/(1 - p(d)) = p(v), so the
    induced distribution must equal the filtered sampling distribution
    IDENTICALLY. Enumerated over every vocab entry from the
    implementation's own tensors (no Monte Carlo), then the actual
    key-driven `accept_candidates` is checked against the enumeration
    empirically."""
    from nanorlhf_tpu.sampler.sampler import filtered_logits_full
    from nanorlhf_tpu.sampler.speculative import accept_candidates

    V, k = 8, 2
    logits = jnp.asarray(
        np.random.default_rng(0).normal(size=(1, k + 1, V)) * 2.0,
        jnp.float32,
    )
    sp = dict(temperature=0.8, top_p=0.9, top_k=V, approx_top_k=False)
    filt = filtered_logits_full(logits, sp["temperature"], sp["top_p"],
                                sp["top_k"], sp["approx_top_k"])
    target = np.asarray(jax.nn.softmax(filt, axis=-1))       # [1, k+1, V]
    for d0 in range(V):
        drafts = jnp.asarray([[d0, (d0 + 3) % V]], jnp.int32)
        # enumerate position 0: accept prob + residual distribution, built
        # exactly the way accept_candidates builds them
        p_d = target[0, 0, d0]
        masked = np.asarray(filt)[0, 0].copy()
        masked[d0] = -np.inf
        res = np.exp(masked - masked.max())
        res = res / res.sum() if np.isfinite(masked).any() else res * 0
        induced = (1.0 - p_d) * res
        induced[d0] += p_d
        np.testing.assert_allclose(induced, target[0, 0], atol=1e-6)
        # and the sampler follows the enumerated law
        keys = jax.random.split(jax.random.PRNGKey(d0 + 1), 3000)
        toks = np.asarray(jax.vmap(
            lambda kk: accept_candidates(
                logits, drafts, kk, greedy=False, **sp
            )[0][0, 0]
        )(keys))
        counts = np.bincount(toks, minlength=V) / len(toks)
        np.testing.assert_allclose(counts, target[0, 0], atol=0.035)


def test_sampled_spec_second_token_distribution_matches_monolithic():
    """End to end over the Markov cycle model (peak 2.5 → the modal next
    token carries ~45% mass, the rest spread): the SECOND generated token,
    conditioned on the first, must follow the exact filtered distribution
    — position 2 always rides the verify/accept path (draft accepted OR
    residual-corrected), so this pins the full rejection pipeline, not
    just the prefill draw the monolithic loop shares."""
    from nanorlhf_tpu.core.model import decode_step, init_kv_cache, prefill
    from nanorlhf_tpu.sampler.sampler import filtered_logits_full

    sigma = [(3 * t + 1) % 16 for t in range(16)]
    cfg, params = cycle_model(sigma, vocab=16, peak=2.5)
    model = (cfg, params)
    ids, mask = _left_pad([[5, 6, 7, 8]], 4)
    temp, top_p = 1.0, 0.9
    outs = []
    for s in range(800):
        out, _ = _gen(model, key=s, spec_k=2, spec_ngram=1, max_tokens=2,
                      prompts=(ids, mask), temperature=temp, top_p=top_p,
                      top_k=0)
        outs.append(np.asarray(out)[0])
    outs = np.stack(outs)                                    # [800, 2]
    # P(t1 | t0) for the modal first token, vs the exact filtered dist
    t0 = int(np.bincount(outs[:, 0]).argmax())
    sel = outs[outs[:, 0] == t0, 1]
    caches = init_kv_cache(cfg, 1, 4 + 4, jnp.float32)
    first_logits, caches = prefill(params, cfg, ids, mask, caches)
    key_mask = jnp.zeros((1, 8), bool).at[:, :4].set(mask)
    key_mask = key_mask.at[:, 4].set(True)
    logits, _ = decode_step(params, cfg, jnp.asarray([t0]),
                            jnp.asarray([4]), 4, key_mask, caches)
    target = np.asarray(jax.nn.softmax(
        filtered_logits_full(logits, temp, top_p, 0, True), axis=-1
    ))[0]
    counts = np.bincount(sel, minlength=cfg.vocab_size) / max(len(sel), 1)
    assert len(sel) > 200
    np.testing.assert_allclose(counts, target, atol=0.06)


def test_filtered_logits_full_matches_sample_token_semantics():
    """The full-vocab filter's keep set must equal the sort-based nucleus
    oracle (top_k=0 path) and the k-space candidate/keep rule (top-k path)
    — the guarantee that spec sampling draws from the SAME distribution
    as `_sample_token`."""
    from nanorlhf_tpu.sampler.sampler import (
        _nucleus_candidates, filtered_logits_full, top_p_filter,
    )

    logits = jnp.asarray(
        np.random.default_rng(1).normal(size=(4, 64)) * 3.0, jnp.float32
    )
    full = np.asarray(filtered_logits_full(logits, 1.0, 0.9, 0, True))
    want = np.asarray(top_p_filter(logits, 0.9)) > -np.inf
    np.testing.assert_array_equal(np.isfinite(full), want)

    full_k = np.asarray(filtered_logits_full(logits, 1.0, 0.9, 16, False))
    _, idx, keep = _nucleus_candidates(logits, 0.9, 16, False)
    want_k = np.zeros(full_k.shape, bool)
    want_k[np.arange(4)[:, None], np.asarray(idx)] = np.asarray(keep)
    np.testing.assert_array_equal(np.isfinite(full_k), want_k)


# --------------------------------------------------------------------- #
# model-level verify vs decode_step chain
# --------------------------------------------------------------------- #

def test_decode_verify_matches_decode_step_chain(tiny):
    from nanorlhf_tpu.core.model import (
        decode_step, decode_verify, init_kv_cache, prefill,
    )

    cfg, params = tiny
    ids, mask = _left_pad([[5, 6, 7], [9, 10, 11]], 4)
    B, Tp, K1 = 2, 4, 4
    T_max = Tp + 8
    caches = init_kv_cache(cfg, B, T_max, jnp.float32)
    first_logits, caches0 = prefill(params, cfg, ids, mask, caches)
    cand = jnp.asarray([[20, 21, 22, 23], [30, 31, 32, 33]], jnp.int32)
    plen = jnp.sum(mask, axis=1).astype(jnp.int32)

    # oracle: K1 sequential decode_steps
    key_mask = jnp.zeros((B, T_max), bool).at[:, :Tp].set(mask)
    caches = caches0
    step_logits = []
    for i in range(K1):
        slot = Tp + i
        key_mask = key_mask.at[:, slot].set(True)
        lg, caches = decode_step(params, cfg, cand[:, i], plen + i, slot,
                                 key_mask, caches)
        step_logits.append(np.asarray(lg))

    # one decode_verify over the same candidates
    key_mask0 = jnp.zeros((B, T_max), bool).at[:, :Tp].set(mask)
    positions = plen[:, None] + jnp.arange(K1)[None, :]
    fill = jnp.full((B,), Tp, jnp.int32)
    vlogits, vcaches = decode_verify(params, cfg, cand, positions, fill,
                                     key_mask0, caches0)
    for i in range(K1):
        np.testing.assert_allclose(
            np.asarray(vlogits)[:, i], step_logits[i], atol=1e-6,
            err_msg=f"position {i}",
        )
    # the caches agree on every written slot
    for a, b in zip(vcaches, caches):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_verify_kernel_interpret_matches_oracle(rng):
    from nanorlhf_tpu.ops.decode_attention import (
        decode_verify_attention, reference_decode_verify_attention,
    )

    B, H, KV, T, Tq, hd = 2, 4, 2, 256, 5, 32
    q = jnp.asarray(rng.standard_normal((B, H, Tq, hd)).astype(np.float32))
    kc = jnp.asarray(rng.standard_normal((B, KV, T, hd)).astype(np.float32))
    vc = jnp.asarray(rng.standard_normal((B, KV, T, hd)).astype(np.float32))
    start = jnp.asarray([0, 17], jnp.int32)
    fill = jnp.asarray([120, 249], jnp.int32)   # row 1 crosses a block edge
    got = decode_verify_attention(q, kc, vc, start, fill, block_k=128)
    want = reference_decode_verify_attention(q, kc, vc, start, fill)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


# --------------------------------------------------------------------- #
# wiring: guard, stats plumbing, instrumented driver
# --------------------------------------------------------------------- #

def test_spec_with_compaction_raises(tiny):
    with pytest.raises(ValueError, match="compaction"):
        _gen(tiny, spec_k=2, compaction_segments=2)


def test_instrumented_driver_matches_and_emits_spans(tiny):
    from nanorlhf_tpu.telemetry import SpanTracer

    cfg, params = tiny
    ids, mask = _left_pad([[5, 6, 7, 8]], 5)
    sp = SamplingParams(greedy=True, max_tokens=12, spec_k=3)
    plain = generate(params, cfg, ids, mask, jax.random.PRNGKey(2), sp,
                     eos_token_id=EOS, pad_token_id=PAD)
    tracer = SpanTracer(enabled=True)
    stats = []
    traced = generate(params, cfg, ids, mask, jax.random.PRNGKey(2), sp,
                      eos_token_id=EOS, pad_token_id=PAD,
                      spec_stats_out=stats, tracer=tracer)
    np.testing.assert_array_equal(np.asarray(plain), np.asarray(traced))
    names = {e["name"] for e in tracer.trace_events()}
    assert "rollout.draft" in names and "rollout.verify" in names
    assert stats and _stat(stats[0], "verify_steps") >= 1


def test_trainer_emits_acceptance_metrics(tmp_path):
    """2-update CPU smoke with rollout_spec_k on: the per-update metrics
    rows must carry rollout/draft_acceptance + rollout/accepted_per_step
    (docs/METRICS.md), and training must run end to end over the spec
    rollout path."""
    import json
    import os

    from nanorlhf_tpu.data import ToyTokenizer, load_prompt_dataset
    from nanorlhf_tpu.parallel import MeshConfig
    from nanorlhf_tpu.trainer import AlgoName, RLConfig, RLTrainer

    mcfg = ModelConfig.qwen2_tiny(vocab_size=512)
    tok = ToyTokenizer(vocab_size=512)
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.bfloat16)
    dataset = load_prompt_dataset("synthetic:32", tok, max_prompt_len=16)

    def reward(pmt_and_responses, eos_token):
        return np.asarray([float(len(s) % 3) for s in pmt_and_responses],
                          np.float32)

    cfg = RLConfig(
        algo=AlgoName.GRPO, output_dir=str(tmp_path), response_length=16,
        sample_n=2, per_device_train_batch_size=2,
        gradient_accumulation_steps=1, num_mini_batches=1,
        total_episodes=64, rollout_spec_k=3, rollout_spec_ngram=2,
        use_lora=True, save_steps=0, mesh=MeshConfig(data=-1),
        report_to="jsonl", logging_steps=1, sentinel=False,
    )
    trainer = RLTrainer(cfg, mcfg, tok, params, dataset, reward)
    try:
        trainer.train(num_updates=2)
    finally:
        trainer.close()
    rows = [json.loads(l) for l in open(
        os.path.join(str(tmp_path), "metrics.jsonl")
    ) if l.strip()]
    step_rows = [r for r in rows if "rollout/draft_acceptance" in r]
    assert len(step_rows) >= 2
    for r in step_rows:
        assert 0.0 <= r["rollout/draft_acceptance"] <= 1.0
        assert r["rollout/accepted_per_step"] >= 1.0
        assert r["rollout/spec_verify_steps"] >= 1.0
