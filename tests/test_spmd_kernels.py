"""Pallas kernels under a sharded mesh (ModelConfig.spmd_mesh hints).

GSPMD has no partitioning rule for a custom call: without the shard_map
wrap at the kernel call sites, a batch-sharded step ALL-GATHERS q/k/v (and
during decode, the whole KV cache) onto every device. These tests pin:
  - numerics: sharded pallas == unsharded XLA reference (fwd, grad, decode)
  - partitioning: no activation/cache-sized all-gathers in compiled HLO
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nanorlhf_tpu.core import ModelConfig, init_params, padded_forward_logits
from nanorlhf_tpu.data import ToyTokenizer
from nanorlhf_tpu.parallel import MeshConfig, batch_sharding, make_mesh
from nanorlhf_tpu.parallel.mesh import shard_params
from nanorlhf_tpu.sampler import SamplingParams, generate


# (4,2,1): batch over data*fsdp, heads replicated.  (2,2,2): tensor=2 also
# shards the HEAD dim (qwen2_tiny H=4, KV=2 both divide) — exercises the GQA
# q/kv-head shard alignment inside the kernels.
MESHES = [MeshConfig(4, 2, 1), MeshConfig(2, 2, 2)]


def _setup(vocab=128, mesh_cfg=MESHES[0]):
    mesh = make_mesh(mesh_cfg)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=vocab)
    spmd = dict(spmd_mesh=mesh, spmd_batch_axes=("data", "fsdp"),
                spmd_head_axis="tensor")
    mcfg_p = dataclasses.replace(mcfg, attention_impl="pallas", **spmd)
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(4, vocab, (8, 32)).astype(np.int32)
    )
    return mesh, mcfg, mcfg_p, params, ids


@pytest.mark.parametrize("mesh_cfg", MESHES)
def test_sharded_flash_forward_matches_xla(mesh_cfg):
    mesh, mcfg, mcfg_p, params, ids = _setup(mesh_cfg=mesh_cfg)
    ref = padded_forward_logits(params, mcfg, ids, 0)
    out = jax.jit(lambda p, i: padded_forward_logits(p, mcfg_p, i, 0))(
        shard_params(params, mesh), jax.device_put(ids, batch_sharding(mesh))
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def test_sharded_flash_no_activation_allgather():
    """Param (fsdp) all-gathers are expected; q/k/v-sized ones are the bug."""
    mesh, mcfg, mcfg_p, params, ids = _setup()
    f = jax.jit(lambda p, i: padded_forward_logits(p, mcfg_p, i, 0))
    hlo = f.lower(
        shard_params(params, mesh), jax.device_put(ids, batch_sharding(mesh))
    ).compile().as_text()
    B, T = ids.shape
    H = mcfg.num_attention_heads
    bad = [
        l for l in hlo.splitlines()
        if "all-gather" in l and (f"[{B},{H},{T}," in l or f"[{B},{T}" in l)
    ]
    assert not bad, f"activation-sized all-gather around the kernel:\n{bad[:3]}"


def test_sharded_flash_grad_matches_xla():
    """Differentiation through shard_map(custom_vjp(pallas)) — the update
    path. Gradient wrt the embedding must match the unsharded XLA grad."""
    mesh, mcfg, mcfg_p, params, ids = _setup()

    def loss(p, cfg, i):
        lg = padded_forward_logits(p, cfg, i, 0)
        return (lg.astype(jnp.float32) ** 2).mean()

    g_ref = jax.grad(loss)(params, mcfg, ids)["embed_tokens"]
    g_sh = jax.jit(jax.grad(lambda p, i: loss(p, mcfg_p, i)))(
        shard_params(params, mesh), jax.device_put(ids, batch_sharding(mesh))
    )["embed_tokens"]
    np.testing.assert_allclose(
        np.asarray(g_sh), np.asarray(g_ref), atol=2e-5
    )


@pytest.mark.parametrize("kv_quant", ["none", "int8"])
@pytest.mark.parametrize("mesh_cfg", MESHES)
def test_sharded_decode_kernel_matches_xla(kv_quant, mesh_cfg):
    """generate() with the decode kernel engaged (impl=pallas) on a sharded
    batch: greedy decode must be token-identical to the unsharded XLA run.
    Covers both the exact and the q8 prefix-bounded kernels, with and
    without head sharding (tensor=2)."""
    mesh, mcfg, mcfg_p, params, ids = _setup(mesh_cfg=mesh_cfg)
    tok = ToyTokenizer(vocab_size=128)
    mcfg_q = dataclasses.replace(mcfg, kv_cache_quant=kv_quant)
    mcfg_pq = dataclasses.replace(mcfg_p, kv_cache_quant=kv_quant)
    mask = ids != tok.pad_token_id
    sp = SamplingParams(greedy=True, max_tokens=12)
    ref = np.asarray(generate(params, mcfg_q, ids, mask, jax.random.PRNGKey(3),
                              sp, eos_token_id=3, pad_token_id=0))
    from jax.sharding import NamedSharding, PartitionSpec as P

    out = np.asarray(generate(
        jax.device_put(params, NamedSharding(mesh, P())), mcfg_pq,
        jax.device_put(ids, batch_sharding(mesh)),
        jax.device_put(mask, batch_sharding(mesh)),
        jax.random.PRNGKey(3), sp, eos_token_id=3, pad_token_id=0,
    ))
    np.testing.assert_array_equal(out, ref)
