"""Telemetry subsystem (nanorlhf_tpu/telemetry/, docs/OBSERVABILITY.md):

- SpanTracer records cross-thread spans/counters into a bounded buffer +
  flight-recorder ring, disabled is a no-op, and the written trace.json
  passes the Chrome trace-event schema validator (the tier-1 CI gate);
- the flight recorder lands `blackbox_<step>.json` on a fault-injected
  sentinel trip, tagged with the quarantined rollout index;
- a 2-update orchestrated smoke train with telemetry on produces a
  Perfetto-loadable trace whose producer-thread generation spans overlap
  the trainer's update spans, and perf/mfu + perf/tokens_per_sec_update
  reach metrics.jsonl (the ISSUE-4 acceptance);
- ProfileWindow opens/closes the XLA profiler around exactly the
  configured updates (cfg knob + trigger file), and trace_profile stays
  start/stop-balanced when the profiled body raises;
- MetricsLogger rows stay pure scalars under perf/ keys and its atexit
  close barrier is registered/unregistered correctly.
"""

import json
import math
import os
import threading
import time

import numpy as np
import pytest

from nanorlhf_tpu.telemetry import (
    BACKEND_COMPILE_EVENT,
    RecompileCounter,
    SpanTracer,
    peak_flops_per_chip,
    recompile_counter,
    update_flops,
    validate_trace_events,
    validate_trace_file,
)
from nanorlhf_tpu.trainer import AlgoName
from nanorlhf_tpu.trainer.metrics import MetricsLogger
from nanorlhf_tpu.utils.profiling import PhaseTimer, ProfileWindow, trace_profile

from test_trainer_smoke import make_trainer


def _metric_rows(outdir):
    rows = []
    with open(outdir / "metrics.jsonl") as f:
        for line in f:
            r = json.loads(line)
            if "samples" not in r:
                rows.append(r)
    return rows


# ---------------------------------------------------------------------------
# SpanTracer units (jax-free)
# ---------------------------------------------------------------------------


def test_tracer_disabled_is_noop(tmp_path):
    tr = SpanTracer(enabled=False)
    with tr.span("x", step=1) as args:
        assert args == {}
    tr.add_complete("y", 0.0, 1.0)
    tr.instant("z")
    tr.counter("c", 3)
    assert tr.write_trace(str(tmp_path / "t.json")) is None
    assert tr.dump_blackbox(str(tmp_path), 0, "test") is None
    assert not (tmp_path / "t.json").exists()
    assert tr.dropped == 0


def test_spans_nest_and_validate(tmp_path):
    tr = SpanTracer(enabled=True)
    with tr.span("outer", step=1) as args:
        args["rollout_index"] = 7  # correlation id learned mid-span
        with tr.span("inner"):
            time.sleep(0.001)
    tr.instant("marker", verdict="spike")
    tr.counter("depth", 2)
    events = tr.trace_events()
    assert validate_trace_events(events) == []
    outer = [e for e in events if e.get("name") == "outer"]
    assert outer[0]["args"]["rollout_index"] == 7
    # thread-name metadata for the recording thread is present
    assert any(e["ph"] == "M" and e["name"] == "thread_name" for e in events)
    path = tr.write_trace(str(tmp_path / "trace.json"))
    assert validate_trace_file(path) == []
    payload = json.load(open(path))
    assert payload["otherData"]["spans_dropped"] == 0


def test_cross_thread_spans_get_distinct_tracks():
    tr = SpanTracer(enabled=True)

    def work():
        with tr.span("producer.work"):
            pass

    t = threading.Thread(target=work, name="fake-producer")
    t.start()
    t.join()
    with tr.span("trainer.work"):
        pass
    evs = [e for e in tr.trace_events() if e["ph"] == "X"]
    tids = {e["name"]: e["tid"] for e in evs}
    assert tids["producer.work"] != tids["trainer.work"]


def test_logical_tracks_and_counters():
    tr = SpanTracer(enabled=True)
    with tr.span("ckpt.save", track="ckpt", step=3):
        pass
    tr.counter("staleness", np.float32(1.0))  # numpy scalar coerced
    events = tr.trace_events()
    assert validate_trace_events(events) == []
    ckpt = next(e for e in events if e.get("name") == "ckpt.save")
    names = {e["args"]["name"] for e in events
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert "ckpt" in names and "counters" in names
    c = next(e for e in events if e["ph"] == "C")
    assert c["args"]["value"] == 1.0
    # logical-track tids are small synthetic ints, not thread idents
    assert ckpt["tid"] < 1000


def test_event_buffer_bounded_ring_keeps_recent():
    tr = SpanTracer(enabled=True, max_events=5, ring_len=3)
    for i in range(10):
        tr.add_complete(f"s{i}", float(i), 0.5)
    assert tr.dropped == 5
    assert len([e for e in tr.trace_events() if e["ph"] == "X"]) == 5
    ring = tr.snapshot_blackbox(0, "test")["spans"]
    assert [e["name"] for e in ring] == ["s7", "s8", "s9"]


def test_async_events_may_overlap_but_x_spans_may_not():
    tr = SpanTracer(enabled=True)
    # rollout_ahead readiness windows overlap — async b/e pairs are legal
    tr.add_async("rollout.generate", 0.0, 100.0, aid=0, track="rollout")
    tr.add_async("rollout.generate", 50.0, 100.0, aid=1, track="rollout")
    assert validate_trace_events(tr.trace_events()) == []
    # the same shape as complete "X" spans on one track is a violation
    bad = [
        {"name": "a", "ph": "X", "ts": 0.0, "dur": 100.0, "pid": 1, "tid": 1},
        {"name": "b", "ph": "X", "ts": 50.0, "dur": 100.0, "pid": 1, "tid": 1},
    ]
    assert any("partially overlaps" in e for e in validate_trace_events(bad))


def test_validator_catches_missing_keys_and_nan_durations():
    assert validate_trace_events([]) == ["traceEvents missing or empty"]
    errs = validate_trace_events([
        {"name": "no-keys", "ph": "X"},
        {"name": "nan-dur", "ph": "X", "ts": 0.0, "dur": float("nan"),
         "pid": 1, "tid": 1},
        {"name": "bad-ts", "ph": "i", "ts": float("inf"), "pid": 1, "tid": 1},
        {"name": "neg-dur", "ph": "X", "ts": 0.0, "dur": -1.0,
         "pid": 1, "tid": 1},
    ])
    assert len(errs) == 4


def test_blackbox_snapshot_carries_open_spans(tmp_path):
    tr = SpanTracer(enabled=True)
    entered = threading.Event()
    release = threading.Event()

    def stuck():
        with tr.span("rollout.generate", rollout_index=4):
            entered.set()
            release.wait(5.0)

    t = threading.Thread(target=stuck, name="rollout-producer")
    t.start()
    entered.wait(5.0)
    try:
        bb = tr.snapshot_blackbox(9, "producer_failure")
    finally:
        release.set()
        t.join()
    opened = [s for s in bb["open_spans"] if s["name"] == "rollout.generate"]
    assert opened and opened[0]["thread"] == "rollout-producer"
    assert opened[0]["args"]["rollout_index"] == 4
    path = tr.dump_blackbox(str(tmp_path), 9, "producer_failure",
                            extra={"error": "boom"})
    assert os.path.basename(path) == "blackbox_9.json"
    assert json.load(open(path))["extra"]["error"] == "boom"


def test_span_args_coerced_to_json_scalars(tmp_path):
    tr = SpanTracer(enabled=True)
    tr.add_complete("s", 0.0, 1.0, a=np.float32(2.5), b=float("nan"),
                    c=object(), d=None, e=True)
    ev = [e for e in tr.trace_events() if e["ph"] == "X"][0]
    assert ev["args"]["a"] == 2.5
    assert isinstance(ev["args"]["b"], str)  # non-finite → stringified
    assert isinstance(ev["args"]["c"], str)
    assert ev["args"]["d"] is None and ev["args"]["e"] is True
    # the written file is valid JSON end to end
    assert validate_trace_file(tr.write_trace(str(tmp_path / "t.json"))) == []


# ---------------------------------------------------------------------------
# MFU accounting + recompile counter
# ---------------------------------------------------------------------------


def test_update_flops_napkin_model():
    # forward-only tokens at 2N, trained tokens at 3·2N
    assert update_flops(10, decode_tokens=3) == 60.0
    assert update_flops(10, train_tokens=3) == 180.0
    assert update_flops(
        10, decode_tokens=1, prefill_tokens=2, score_tokens=3, train_tokens=4
    ) == (1 + 2 + 3) * 20.0 + 4 * 60.0


def test_peak_flops_lookup():
    v5p, known = peak_flops_per_chip("TPU v5p", "tpu")
    assert known and v5p == 459e12
    trillium, known = peak_flops_per_chip("TPU v6e", "tpu")
    assert known and trillium == 918e12
    unknown, known = peak_flops_per_chip("TPU v99", "tpu")
    assert not known and unknown > 0
    cpu, known = peak_flops_per_chip("cpu", "cpu")
    assert not known and cpu > 0  # finite so the MFU series stays plottable


def test_recompile_counter_listener_and_singleton():
    c = RecompileCounter()
    c._on_event(BACKEND_COMPILE_EVENT, 1.5)
    c._on_event("/jax/some/other/event", 9.0)
    assert c.count == 1 and c.seconds == 1.5
    assert recompile_counter() is recompile_counter()  # process-global


def test_recompile_counter_sees_real_backend_compile():
    import jax
    import jax.numpy as jnp

    c = recompile_counter()
    before = c.count
    # a fresh traced constant → new cache key → a REAL backend compile
    # (in-memory jit cache and the persistent compile cache can't serve it)
    salt = float(np.random.default_rng().random())
    out = jax.jit(lambda x: x * salt)(jnp.ones((3,)))
    out.block_until_ready()
    assert c.count > before


# ---------------------------------------------------------------------------
# PhaseTimer + ProfileWindow + trace_profile
# ---------------------------------------------------------------------------


def test_phase_timer_monotonic_and_spans():
    tr = SpanTracer(enabled=True)
    timer = PhaseTimer(tracer=tr)
    with timer.phase("rollout"):
        time.sleep(0.002)
    s = timer.summary()
    assert s["time/rollout_s"] > 0
    assert timer.totals == {}  # summary resets per-update totals...
    assert timer.cumulative["rollout"] > 0  # ...but never the run totals
    names = [e["name"] for e in tr.trace_events() if e["ph"] == "X"]
    assert "trainer.rollout" in names


def test_trace_profile_balanced_on_exception(tmp_path):
    d1, d2 = str(tmp_path / "p1"), str(tmp_path / "p2")
    with pytest.raises(ValueError, match="boom"):
        with trace_profile(d1):
            raise ValueError("boom")
    assert os.path.isdir(d1)  # dir created even though the body raised
    # the profiler was stopped by the finally — a new trace can start
    with trace_profile(d2):
        pass
    assert os.path.isdir(d2)


def test_profile_window_cfg_step_and_trigger_file(tmp_path):
    trigger = str(tmp_path / "PROFILE")
    w = ProfileWindow(str(tmp_path / "prof"), at_step=2, num_steps=1,
                      trigger_file=trigger)
    w.poll(1)
    assert not w.active
    w.poll(2)
    assert w.active and os.path.isdir(str(tmp_path / "prof"))
    w.poll(3)
    assert not w.active and w.windows == 1
    w.poll(4)
    assert not w.active  # the cfg-driven window fires once per run
    # on-demand window: touching the trigger file opens one and consumes it
    open(trigger, "w").close()
    w.poll(5)
    assert w.active and not os.path.exists(trigger)
    w.stop()  # idempotent close (the trainer's close() path)
    w.stop()
    assert not w.active and w.windows == 2


# ---------------------------------------------------------------------------
# MetricsLogger satellites
# ---------------------------------------------------------------------------


def test_metrics_rows_stay_pure_scalars(tmp_path):
    lg = MetricsLogger(str(tmp_path), "jsonl")
    lg.log(1, 16, {
        "perf/mfu": np.float32(0.31),
        "perf/tokens_per_sec_update": np.float64(1234.5),
        "perf/recompiles": 3,
        "telemetry/spans_dropped": 0.0,
    })
    lg.close()
    rows = _metric_rows(tmp_path)
    assert len(rows) == 1
    for k, v in rows[0].items():
        assert isinstance(v, (int, float)), f"{k} is {type(v)}"
    assert rows[0]["perf/mfu"] == pytest.approx(0.31, rel=1e-6)


def test_metrics_logger_atexit_close_registered(tmp_path, monkeypatch):
    import atexit

    registered, unregistered = [], []
    monkeypatch.setattr(atexit, "register",
                        lambda fn, *a, **k: (registered.append(fn), fn)[1])
    monkeypatch.setattr(atexit, "unregister",
                        lambda fn: unregistered.append(fn))
    lg = MetricsLogger(str(tmp_path), "jsonl")
    # the abnormal-exit flush barrier is armed at construction
    assert len(registered) == 1 and registered[0].__self__ is lg
    lg.log(1, 1, {"a": 1.0})
    lg.close()
    assert unregistered, "close() must disarm the atexit barrier"
    lg.close()  # idempotent: handles already None
    assert _metric_rows(tmp_path)[0]["a"] == 1.0
    nosink = MetricsLogger(str(tmp_path), "none")
    assert len(registered) == 1  # nothing to flush → no barrier armed
    nosink.close()


# ---------------------------------------------------------------------------
# flight recorder via deterministic fault injection (ISSUE-4 satellite)
# ---------------------------------------------------------------------------


def test_flight_recorder_blackbox_on_sentinel_trip(tmp_path, monkeypatch):
    """NANORLHF_FAULT poisons update 2's observed stats → sentinel trip →
    the resilience layer dumps `blackbox_2.json` next to the checkpoint it
    rolls back to, with the tripped step's span carrying the quarantined
    rollout index."""
    monkeypatch.setenv("NANORLHF_FAULT", "update.step:at=2,action=nan")
    tr = make_trainer(AlgoName.REINFORCE, tmp_path, total_episodes=48,
                      telemetry=True)
    state = tr.train()
    tr.close()
    assert state["global_step"] == 3
    assert tr.sentinel.quarantined == {1}  # update 2 consumed rollout 1

    bb_path = tmp_path / "reinforce" / "blackbox_2.json"
    assert bb_path.exists(), os.listdir(tmp_path / "reinforce")
    bb = json.load(open(bb_path))
    assert bb["reason"] == "sentinel_trip"
    assert bb["extra"]["rollout_index"] == 1
    assert bb["extra"]["verdict"] == "nonfinite"
    # every ring event is schema-shaped (ph/ts/pid/tid, finite ts)
    assert bb["spans"], "flight-recorder ring is empty"
    for e in bb["spans"]:
        assert {"ph", "ts", "pid", "tid"} <= set(e)
        assert math.isfinite(e["ts"])
    # the tripped update's span is in the ring, tagged quarantined
    trips = [e for e in bb["spans"] if e.get("name") == "train.update"
             and e.get("args", {}).get("quarantined")]
    assert trips, [e.get("name") for e in bb["spans"]]
    assert trips[-1]["args"]["rollout_index"] == 1
    assert trips[-1]["args"]["sentinel_verdict"] == "nonfinite"
    # the sentinel.trip instant marker made it too
    assert any(e.get("name") == "sentinel.trip" for e in bb["spans"])


# ---------------------------------------------------------------------------
# 2-update telemetry smoke (ISSUE-4 acceptance; the named tier1.yml step)
# ---------------------------------------------------------------------------


def test_telemetry_smoke_trace_schema_overlap_and_perf_metrics(tmp_path):
    """Orchestrated 2-update GRPO smoke with telemetry on: trace.json is
    schema-valid, producer-thread generation spans overlap trainer update
    spans (the pipelining picture), spans carry correlation args, and the
    perf/mfu + perf/tokens_per_sec_update rows reach metrics.jsonl."""
    tr = make_trainer(AlgoName.GRPO, tmp_path, total_episodes=32,
                      telemetry=True, rollout_orchestrator=True,
                      max_staleness=2, sampler_logprob_capture=True)
    state = tr.train()
    assert state["global_step"] == 2
    trace_path = tmp_path / "grpo" / "trace.json"
    assert trace_path.exists()
    assert validate_trace_file(str(trace_path)) == []

    evs = json.load(open(trace_path))["traceEvents"]
    upd = [e for e in evs if e.get("name") == "train.update"]
    gen = [e for e in evs if e.get("name") == "rollout.generate"
           and e.get("ph") == "X"]
    assert len(upd) == 2 and len(gen) >= 2
    # producer spans live on their own thread track
    assert {e["tid"] for e in gen}.isdisjoint({e["tid"] for e in upd})
    for e in upd:
        assert {"step", "rollout_index", "staleness",
                "policy_version"} <= set(e["args"])
    for e in gen:
        assert {"rollout_index", "policy_version"} <= set(e["args"])
    # generation wall-clock ran concurrently with trainer update spans
    overlap = sum(
        max(0.0, min(g["ts"] + g["dur"], u["ts"] + u["dur"])
            - max(g["ts"], u["ts"]))
        for g in gen for u in upd
    )
    assert overlap > 0.0
    # checkpoint I/O + reward dispatch got their logical tracks
    names = {e.get("name") for e in evs}
    assert "ckpt.save" in names and "reward.dispatch" in names

    rows = _metric_rows(tmp_path / "grpo")
    last = rows[-1]
    assert last["perf/mfu"] > 0.0
    assert last["perf/tokens_per_sec_update"] > 0.0
    assert last["perf/tokens_per_sec_step"] > 0.0
    assert last["perf/recompiles"] >= 1.0  # this run compiled something
    assert last["telemetry/spans_dropped"] == 0.0
    assert "orchestrator/consumer_wait_s" in last
    assert "orchestrator/producer_gate_wait_s" in last
    tr.close()


def test_profile_window_via_trainer_config(tmp_path):
    """cfg.profile_at_step wires the (previously unused) trace_profile
    through the trainer: the XLA profile dir is created for exactly the
    configured window and the window is closed by end-of-train."""
    prof_dir = str(tmp_path / "xla_prof")
    tr = make_trainer(AlgoName.REINFORCE, tmp_path, total_episodes=16,
                      profile_at_step=1, profile_dir=prof_dir)
    tr.train()
    tr.close()
    assert os.path.isdir(prof_dir)
    assert tr.profile_window.windows == 1
    assert not tr.profile_window.active


def test_telemetry_off_writes_no_trace(tmp_path):
    """telemetry=False is the default and must leave no trace/blackbox
    artifacts (the acceptance's 'disabled is the default')."""
    tr = make_trainer(AlgoName.REINFORCE, tmp_path, total_episodes=16)
    assert tr.cfg.telemetry is False
    tr.train()
    tr.close()
    out = tmp_path / "reinforce"
    assert not (out / "trace.json").exists()
    assert not list(out.glob("blackbox_*.json"))
    # perf accounting is emitted regardless of the tracer flag
    last = _metric_rows(out)[-1]
    assert "perf/mfu" in last and "perf/tokens_per_sec_update" in last
