"""Native token-cache file (native/token_cache.cpp + data/token_cache.py):
roundtrip, C++↔Python byte-format interop, validation, pipeline wiring."""

import numpy as np
import pytest

from nanorlhf_tpu import native
from nanorlhf_tpu.data import datasets as datasets_mod
from nanorlhf_tpu.data import load_prompt_dataset
from nanorlhf_tpu.data.token_cache import (
    _read_py,
    _write_py,
    corpus_fingerprint,
    load_token_cache,
    save_token_cache,
)
from nanorlhf_tpu.data.tokenizer import ToyTokenizer

ROWS = [[1, 2, 3], [7], [], [5, 6, 7, 8, 9], [2**31 - 1, -4]]
FP = corpus_fingerprint(name="t", seed=0)


def _assert_rows_equal(got, want):
    assert got is not None and len(got) == len(want)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w, np.int32))


def test_roundtrip(tmp_path):
    path = str(tmp_path / "c.tok")
    assert save_token_cache(path, ROWS, FP)
    _assert_rows_equal(load_token_cache(path, FP), ROWS)


def test_fingerprint_mismatch_and_corruption(tmp_path):
    path = str(tmp_path / "c.tok")
    assert save_token_cache(path, ROWS, FP)
    assert load_token_cache(path, FP + 1) is None
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[:-2])  # truncated payload
    assert load_token_cache(path, FP) is None
    assert load_token_cache(str(tmp_path / "missing.tok"), FP) is None


@pytest.mark.skipif(not native.available(), reason="no native toolchain")
def test_cpp_python_interop(tmp_path):
    """The C++ writer and the Python fallback produce the SAME bytes; each
    side reads the other's file."""
    p_cpp = str(tmp_path / "cpp.tok")
    p_py = str(tmp_path / "py.tok")
    assert native.token_cache_write_native(p_cpp, ROWS, FP)
    assert _write_py(p_py, ROWS, FP)
    assert open(p_cpp, "rb").read() == open(p_py, "rb").read()
    # python reader on the C++ file
    offsets, flat, n = _read_py(p_cpp, FP)
    got = [flat[offsets[i]:offsets[i + 1]] for i in range(n)]
    _assert_rows_equal(got, ROWS)
    # native reader on the python file
    view = native.token_cache_open_native(p_py, FP)
    assert view is not None
    _assert_rows_equal([view.row(i) for i in range(view.n_rows)], ROWS)
    view.close()


def test_load_prompt_dataset_cache_hit(tmp_path, monkeypatch):
    """Second identical load must come from the cache (tokenization never
    runs) and be byte-identical; a changed seed must miss."""
    tok = ToyTokenizer(vocab_size=512)
    kw = dict(max_prompt_len=32, seed=3, cache_dir=str(tmp_path))
    ds1 = load_prompt_dataset("synthetic:24", tok, **kw)

    def boom(*a, **k):
        raise AssertionError("tokenized on what should be a cache hit")

    monkeypatch.setattr(datasets_mod, "encode_texts", boom)
    ds2 = load_prompt_dataset("synthetic:24", tok, **kw)
    np.testing.assert_array_equal(ds1.input_ids, ds2.input_ids)
    with pytest.raises(AssertionError):
        load_prompt_dataset("synthetic:24", tok, max_prompt_len=32, seed=4,
                            cache_dir=str(tmp_path))


@pytest.mark.skipif(not native.available(), reason="no native toolchain")
def test_native_rejects_huge_n_rows_header(tmp_path):
    """A corrupt header whose u64 n_rows exceeds what the file can hold must
    be rejected BEFORE any seek-offset arithmetic — the unchecked value can
    overflow the signed fseek offset (UB) and the expected-size computation
    (ADVICE r3). The Python fallback already rejects via ValueError."""
    import struct

    path = str(tmp_path / "c.tok")
    assert native.token_cache_write_native(path, ROWS, FP)
    raw = bytearray(open(path, "rb").read())
    good = bytes(raw)
    for bogus in (2**63 // 8, 2**64 - 1, len(raw)):  # overflow + oversize
        raw[8:16] = struct.pack("<Q", bogus)
        open(path, "wb").write(raw)
        assert native.token_cache_open_native(path, FP) is None
        assert _read_py(path, FP) is None
    # corrupt LAST OFFSET near 2^62: (2^62+total)*4 wraps mod 2^64 back onto
    # the true payload size, so an unbounded reader computes expect ==
    # st_size and returns total_tokens ~ 2^62 (code-review r4 finding) —
    # both readers must reject via the payload-capacity bound
    raw = bytearray(good)
    n = len(ROWS)
    total = sum(len(r) for r in ROWS)
    last_off_at = 24 + n * 8
    raw[last_off_at:last_off_at + 8] = struct.pack("<q", 2**62 + total)
    open(path, "wb").write(raw)
    assert native.token_cache_open_native(path, FP) is None
    assert _read_py(path, FP) is None


def test_load_prompt_dataset_cache_content_sensitive(tmp_path, monkeypatch):
    """Same (name, split, limit, seed, tokenizer) but DIFFERENT corpus
    content must miss the cache and re-tokenize — for HF sources the
    fingerprint hashes the raw texts, so an upstream dataset revision
    change cannot silently serve stale tokens (ADVICE r3). `synthetic:`
    corpora stay params-keyed: their content is fully determined by
    (name, seed, tokenizer identity), so they keep the load-free hit."""
    tok = ToyTokenizer(vocab_size=512)
    kw = dict(max_prompt_len=32, seed=3, cache_dir=str(tmp_path))

    def corpus(tag):
        return [{"chosen": f"\n\nHuman: {tag} question {i}\n\nAssistant: ok"}
                for i in range(8)]

    monkeypatch.setattr(datasets_mod, "_load_hf_dataset",
                        lambda name, split: corpus("v1"))
    load_prompt_dataset("fake/hh", tok, **kw)

    calls = []
    real_encode = datasets_mod.encode_texts

    def counting_encode(*a, **k):
        calls.append(1)
        return real_encode(*a, **k)

    monkeypatch.setattr(datasets_mod, "encode_texts", counting_encode)
    # identical request + identical content -> cache hit, no tokenization
    load_prompt_dataset("fake/hh", tok, **kw)
    assert not calls
    # same request args, different underlying corpus -> must re-tokenize
    monkeypatch.setattr(datasets_mod, "_load_hf_dataset",
                        lambda name, split: corpus("v2"))
    load_prompt_dataset("fake/hh", tok, **kw)
    assert calls
