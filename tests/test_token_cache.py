"""Native token-cache file (native/token_cache.cpp + data/token_cache.py):
roundtrip, C++↔Python byte-format interop, validation, pipeline wiring."""

import numpy as np
import pytest

from nanorlhf_tpu import native
from nanorlhf_tpu.data import datasets as datasets_mod
from nanorlhf_tpu.data import load_prompt_dataset
from nanorlhf_tpu.data.token_cache import (
    _read_py,
    _write_py,
    corpus_fingerprint,
    load_token_cache,
    save_token_cache,
)
from nanorlhf_tpu.data.tokenizer import ToyTokenizer

ROWS = [[1, 2, 3], [7], [], [5, 6, 7, 8, 9], [2**31 - 1, -4]]
FP = corpus_fingerprint(name="t", seed=0)


def _assert_rows_equal(got, want):
    assert got is not None and len(got) == len(want)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w, np.int32))


def test_roundtrip(tmp_path):
    path = str(tmp_path / "c.tok")
    assert save_token_cache(path, ROWS, FP)
    _assert_rows_equal(load_token_cache(path, FP), ROWS)


def test_fingerprint_mismatch_and_corruption(tmp_path):
    path = str(tmp_path / "c.tok")
    assert save_token_cache(path, ROWS, FP)
    assert load_token_cache(path, FP + 1) is None
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[:-2])  # truncated payload
    assert load_token_cache(path, FP) is None
    assert load_token_cache(str(tmp_path / "missing.tok"), FP) is None


@pytest.mark.skipif(not native.available(), reason="no native toolchain")
def test_cpp_python_interop(tmp_path):
    """The C++ writer and the Python fallback produce the SAME bytes; each
    side reads the other's file."""
    p_cpp = str(tmp_path / "cpp.tok")
    p_py = str(tmp_path / "py.tok")
    assert native.token_cache_write_native(p_cpp, ROWS, FP)
    assert _write_py(p_py, ROWS, FP)
    assert open(p_cpp, "rb").read() == open(p_py, "rb").read()
    # python reader on the C++ file
    offsets, flat, n = _read_py(p_cpp, FP)
    got = [flat[offsets[i]:offsets[i + 1]] for i in range(n)]
    _assert_rows_equal(got, ROWS)
    # native reader on the python file
    view = native.token_cache_open_native(p_py, FP)
    assert view is not None
    _assert_rows_equal([view.row(i) for i in range(view.n_rows)], ROWS)
    view.close()


def test_load_prompt_dataset_cache_hit(tmp_path, monkeypatch):
    """Second identical load must come from the cache (tokenization never
    runs) and be byte-identical; a changed seed must miss."""
    tok = ToyTokenizer(vocab_size=512)
    kw = dict(max_prompt_len=32, seed=3, cache_dir=str(tmp_path))
    ds1 = load_prompt_dataset("synthetic:24", tok, **kw)

    def boom(*a, **k):
        raise AssertionError("tokenized on what should be a cache hit")

    monkeypatch.setattr(datasets_mod, "encode_texts", boom)
    ds2 = load_prompt_dataset("synthetic:24", tok, **kw)
    np.testing.assert_array_equal(ds1.input_ids, ds2.input_ids)
    with pytest.raises(AssertionError):
        load_prompt_dataset("synthetic:24", tok, max_prompt_len=32, seed=4,
                            cache_dir=str(tmp_path))
