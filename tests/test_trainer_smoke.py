"""End-to-end trainer smoke tests on the 8-device CPU mesh.

Covers the BASELINE.json smoke config shape (REINFORCE, rule-based reward,
CPU-runnable) plus one pass of every other algorithm — the integration net
the reference never had (SURVEY.md §4).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nanorlhf_tpu.core import ModelConfig, init_params, init_score_head
from nanorlhf_tpu.data import ToyTokenizer, load_prompt_dataset
from nanorlhf_tpu.parallel import MeshConfig
from nanorlhf_tpu.trainer import RLConfig, AlgoName, RLTrainer


def rule_reward(pmt_and_responses, eos_token):
    """Rule-based reward: likes responses that end (contain EOS) and are short."""
    out = []
    for s in pmt_and_responses:
        has_eos = 1.0 if eos_token in s else 0.0
        out.append(has_eos - 0.01 * len(s.split()))
    return np.asarray(out, dtype=np.float32)


def make_trainer(algo: AlgoName, tmp_path, **overrides):
    tok = ToyTokenizer(vocab_size=256)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=256)
    key = jax.random.PRNGKey(0)
    params = init_params(mcfg, key, jnp.float32)
    cfg = RLConfig(
        algo=algo,
        output_dir=str(tmp_path / algo.value),
        response_length=8,
        temperature=1.0,
        sample_n=2,
        total_episodes=32,
        per_device_train_batch_size=1,
        gradient_accumulation_steps=2,
        num_mini_batches=2,
        num_ppo_epochs=1,
        learning_rate=1e-4,
        kl_coef=0.05,
        use_lora=True,
        lora_r=4,
        lora_alpha=8,
        gradient_checkpointing=False,
        mesh=MeshConfig(2, 2, 2),
        save_steps=1,
        report_to="jsonl",
    )
    for k, v in overrides.items():
        setattr(cfg, k, v)
    dataset = load_prompt_dataset("synthetic:64", tok, max_prompt_len=12)
    value_params = None
    if algo == AlgoName.PPO:
        value_params = init_params(mcfg, jax.random.PRNGKey(2), jnp.float32)
        value_params.pop("lm_head", None)
        value_params["score"] = init_score_head(mcfg, jax.random.PRNGKey(3), dtype=jnp.float32)
    return RLTrainer(
        cfg, mcfg, tok, params, dataset, rule_reward, value_params=value_params
    )


def test_reinforce_smoke(tmp_path):
    tr = make_trainer(AlgoName.REINFORCE, tmp_path, advantage_whiten=True)
    # batch = 1*2*2 * world(4) = 16 → 2 updates for 32 episodes
    state = tr.train()
    assert state["global_step"] == 2
    assert (tmp_path / "reinforce" / "metrics.jsonl").exists()
    assert (tmp_path / "reinforce" / "checkpoint-2").exists()


def test_rollout_context_depadding(tmp_path):
    """Batches of short prompts train at a menu-rounded context, not the
    dataset-wide max (r1 de-padding applied to the main trainer)."""
    tr = make_trainer(AlgoName.REINFORCE, tmp_path, total_episodes=16)
    # dataset padded to width 12; all synthetic prompts are much shorter than
    # a padded-out width, so force a wide dataset pad to observe the strip
    wide = np.full((64, 32), tr.tokenizer.pad_token_id, np.int32)
    wide[:, -6:] = tr.dataset.input_ids[:, -6:]
    tr.dataset.input_ids = wide
    tr._iter = tr.dataset.loader(tr.cfg.batch_size, tr.cfg.seed)
    seen = {}
    orig = tr._score_chunk_fn()

    def spy(params, ref_params, qr, context_length):
        seen["ctx"] = context_length
        return orig(params, ref_params, qr, context_length)

    tr._score_fn_cached = spy
    tr.train(num_updates=1)
    assert seen["ctx"] <= 16, f"context not de-padded: {seen['ctx']}"


def test_multiple_ppo_epochs_go_off_policy(tmp_path):
    """num_ppo_epochs=2: the second epoch re-fits on stale rollouts, so the
    importance ratio must move off 1 (the clipping machinery is live) while
    the run stays finite — the off-policy capability the reference's losses
    exist for (`REINFORCE/reinforce_trainer.py:637` comment)."""
    import json

    tr = make_trainer(AlgoName.GRPO, tmp_path, total_episodes=16,
                      num_ppo_epochs=2, learning_rate=5e-3)
    tr.train()
    lines = [
        json.loads(l)
        for l in open(tmp_path / "grpo" / "metrics.jsonl")
        if "samples" not in l
    ]
    m = lines[-1]
    # averaged over both epochs the ratio reflects epoch-2 drift
    assert np.isfinite(m["val/ratio_new"])
    assert m["policy/approxkl_avg_new"] > 0, "second epoch produced no drift"
    assert np.isfinite(m["loss/policy_avg_new"])


@pytest.mark.parametrize(
    "algo", [AlgoName.GRPO, AlgoName.RLOO, AlgoName.RAFT, AlgoName.REMAX, AlgoName.PPO]
)
def test_all_algos_one_update(tmp_path, algo):
    tr = make_trainer(algo, tmp_path, total_episodes=16)
    state = tr.train()
    assert state["global_step"] == 1
    import json

    lines = [
        json.loads(l)
        for l in open(tmp_path / algo.value / "metrics.jsonl")
        if "samples" not in l
    ]
    m = lines[-1]
    assert np.isfinite(m["loss/policy_avg_new"])
    assert np.isfinite(m["eval_objective/rlhf_reward_old"])
    if algo == AlgoName.PPO:
        assert "loss/value_avg_new" in m

    # metric-surface fidelity (docs/METRICS.md): every reference key present
    # with per-algo semantics
    for key in (
        "objective/kl_old", "objective/kl_rollout_old", "objective/entropy_old",
        "objective/non_score_reward_old", "eval_objective/scores_old",
        "policy/approxkl_avg_new", "policy/clipfrac_avg_new",
        "policy/entropy_avg_new", "loss/policy_avg_new", "val/ratio_new",
        "val/ratio_var_new", "val/num_eos_tokens_old", "lr", "eps", "episode",
    ):
        assert key in m, f"missing metric {key}"
        assert np.isfinite(m[key]), f"non-finite metric {key}"
    assert m["policy/entropy_avg_new"] > 0, "true entropy must be positive"
    assert m["lr"] > 0
    if algo == AlgoName.GRPO:
        # GRPO: KL in-loss -> non_score_reward identically 0 (reference
        # hard-codes it, `grpo_trainer.py:730`)
        assert m["objective/non_score_reward_old"] == 0.0
    else:
        # KL-in-reward: non_score_reward is the measured KL penalty — exactly
        # -kl_coef x the rollout token-sum KL (both reduce the same masked
        # tensor). At update 1 both are 0 (LoRA b=0 -> policy == ref), so the
        # identity is the meaningful check, not nonzero-ness.
        assert m["objective/non_score_reward_old"] == pytest.approx(
            -tr.cfg.kl_coef * m["objective/kl_rollout_old"], abs=1e-6
        )


def test_pad_chunk_prime_totals():
    """A prime rollout count no longer degenerates the chunked logprob pass
    to chunk=1 (VERDICT r1 weak #6): fixed-size chunks with a padded tail,
    results sliced back — numerics unchanged."""
    from nanorlhf_tpu.trainer.trainer import pad_chunk

    total, chunk = 97, 16
    data = np.arange(total * 3, dtype=np.float32).reshape(total, 3)
    out = []
    n_calls = 0
    for i in range(0, total, chunk):
        n_real = min(chunk, total - i)
        rows = pad_chunk(data[i : i + chunk], chunk)
        assert rows.shape[0] == chunk  # ONE jit shape for every call
        out.append(rows[:n_real])
        n_calls += 1
    np.testing.assert_array_equal(np.concatenate(out), data)
    assert n_calls == 7  # ceil(97/16), not 97


def test_ppo_value_lora_shrinks_optimizer_state(tmp_path):
    """Value-model LoRA (`PPO/ppo.py:301-332`): the Adam state for the value
    tree covers only adapters + score + embed, and the value backbone never
    drifts during PPO updates."""
    tr_full = make_trainer(AlgoName.PPO, tmp_path, total_episodes=16,
                           value_use_lora=False)
    tr_lora = make_trainer(AlgoName.PPO, tmp_path / "l", total_episodes=16,
                           value_use_lora=True, value_lora_r=4,
                           value_lora_alpha=8)

    def trainable_value_elems(tr):
        trainable, _ = tr._partition(tr._train_tree(tr.params, tr.value_params))
        return sum(
            int(np.prod(x.shape)) for x in jax.tree.leaves(trainable["value"])
            if x is not None
        )

    # LoRA: backbone layers frozen, only adapters + score + embed trainable —
    # strictly fewer optimizer-tracked elements than full fine-tuning
    assert trainable_value_elems(tr_lora) < trainable_value_elems(tr_full)

    backbone_before = [
        np.asarray(x).copy() for x in jax.tree.leaves(tr_lora.value_params["layers"])
    ]
    tr_lora.train(num_updates=1)
    for a, b in zip(backbone_before, jax.tree.leaves(tr_lora.value_params["layers"])):
        np.testing.assert_array_equal(a, np.asarray(b))
    assert "lora" in tr_lora.value_params


def test_sampler_logprob_capture_grpo(tmp_path):
    """Opt-in capture path: one GRPO update trains with sampler-captured
    logprobs (policy scoring pass skipped); the epoch-1 ratio stays ~1 and
    the drift guard metric is emitted."""
    import json

    tr = make_trainer(AlgoName.GRPO, tmp_path, total_episodes=16,
                      sampler_logprob_capture=True)
    state = tr.train()
    assert state["global_step"] == 1
    lines = [
        json.loads(l)
        for l in open(tmp_path / "grpo" / "metrics.jsonl")
        if "samples" not in l
    ]
    m = lines[-1]
    assert "sampler_capture/ratio_drift_new" in m
    # f32 tiny model: decode and scoring numerics agree to float noise
    assert m["sampler_capture/ratio_drift_new"] < 1e-2
    assert np.isfinite(m["loss/policy_avg_new"])


def test_rollout_top_k_reaches_sampler(tmp_path, monkeypatch):
    """RLConfig.rollout_top_k / rollout_approx_top_k flow into the
    SamplingParams the rollout uses — the r1-zero launcher relies on
    top_k=0 giving the exact untruncated nucleus (VERDICT r3 #6)."""
    import nanorlhf_tpu.trainer.trainer as trainer_mod

    seen = []
    real_generate = trainer_mod.generate

    def spy_generate(params, config, ids, mask, key, sampling, **kw):
        seen.append(sampling)
        return real_generate(params, config, ids, mask, key, sampling, **kw)

    monkeypatch.setattr(trainer_mod, "generate", spy_generate)
    trainer = make_trainer(AlgoName.GRPO, tmp_path, total_episodes=16,
                           rollout_top_k=0, rollout_approx_top_k=False)
    trainer.train(num_updates=1)
    assert seen and seen[0].top_k == 0 and seen[0].approx_top_k is False

    # the SPARSE trainer (the r1-zero path the top_k=0 default targets)
    # builds its own SamplingParams — it must thread the knobs too
    # (code-review r4: it silently fell back to the k=64 pre-trim)
    import nanorlhf_tpu.trainer.sparse_grpo as sparse_mod
    from nanorlhf_tpu.trainer.sparse_grpo import SparseGRPOTrainer

    seen_sparse = []

    def spy_sparse(params, config, ids, mask, key, sampling, **kw):
        seen_sparse.append(sampling)
        return real_generate(params, config, ids, mask, key, sampling, **kw)

    monkeypatch.setattr(sparse_mod, "generate", spy_sparse)
    tok = ToyTokenizer(vocab_size=256)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=256)
    cfg = RLConfig(
        algo=AlgoName.GRPO, output_dir=str(tmp_path / "sparse"),
        response_length=8, temperature=1.0, sample_n=2, total_episodes=32,
        per_device_train_batch_size=4, gradient_accumulation_steps=1,
        num_mini_batches=1, use_lora=False, gradient_checkpointing=False,
        mesh=MeshConfig(-1, 1, 1), save_steps=0, report_to="none",
        rollout_top_k=0, rollout_approx_top_k=False,
    )
    st = SparseGRPOTrainer(
        cfg, mcfg, tok, init_params(mcfg, jax.random.PRNGKey(1), jnp.float32),
        load_prompt_dataset("synthetic:64", tok, max_prompt_len=12),
        rule_reward,
    )
    st.train(num_updates=1)
    assert seen_sparse and seen_sparse[0].top_k == 0
    assert seen_sparse[0].approx_top_k is False

    from nanorlhf_tpu.entrypoints.grpo_r1 import build_config

    assert build_config().rollout_top_k == 0


def test_ref_free_mode_kl0(tmp_path):
    """kl_coef == 0 auto-drops the reference model (r1-zero parity — the
    reference loads NO ref model on that path, `grpo_r1.py:138`): no ref
    weight copy, no ref half of the scoring pass, and the training
    trajectory is BIT-IDENTICAL to a forced-ref run, because ref logprobs
    only ever enter terms multiplied by kl_coef. score_ref_logprobs=True
    forces ref scoring (e.g. to monitor KL drift at coef 0)."""
    t_free = make_trainer(AlgoName.GRPO, tmp_path, kl_coef=0.0,
                          output_dir=str(tmp_path / "free"))
    assert t_free.ref_params is None        # no 2nd weight copy in HBM
    t_free.train(num_updates=2)

    t_full = make_trainer(AlgoName.GRPO, tmp_path, kl_coef=0.0,
                          score_ref_logprobs=True,
                          output_dir=str(tmp_path / "full"))
    assert t_full.ref_params is not None
    t_full.train(num_updates=2)

    for a, b in zip(jax.tree.leaves(t_free.params),
                    jax.tree.leaves(t_full.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # KL metrics read 0 (no reference model exists; the GRPO update-pass
    # refkl stand-in would otherwise report KL-to-old-policy)
    import json

    rows = [json.loads(l) for l in open(tmp_path / "free" / "metrics.jsonl")
            if "objective/kl_old" in l]
    assert rows and all(r["objective/kl_old"] == 0.0 for r in rows)
    assert all(r["objective/kl_rollout_old"] == 0.0 for r in rows)

    # capture + ref-free: the scoring pass disappears entirely — still runs
    t_cap = make_trainer(AlgoName.GRPO, tmp_path, kl_coef=0.0,
                         sampler_logprob_capture=True,
                         output_dir=str(tmp_path / "cap"))
    t_cap.train(num_updates=1)

    # dropping the ref while its KL coefficient is live is rejected — it
    # would silently swap the configured objective
    with pytest.raises(ValueError, match="score_ref_logprobs"):
        make_trainer(AlgoName.GRPO, tmp_path, kl_coef=0.01,
                     score_ref_logprobs=False,
                     output_dir=str(tmp_path / "bad"))

    # PPO value-init with a None ref (ref-free): the ref forward is skipped
    # and the returned tree still regresses
    from nanorlhf_tpu.core import init_score_head
    from nanorlhf_tpu.trainer.value_init import (
        ValueInitConfig, finetune_value_model)

    tok = ToyTokenizer(vocab_size=256)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=256)
    pol = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    val = init_params(mcfg, jax.random.PRNGKey(1), jnp.float32)
    val.pop("lm_head", None)
    val["score"] = init_score_head(mcfg, jax.random.PRNGKey(2),
                                   dtype=jnp.float32)
    prompts = load_prompt_dataset("synthetic:8", tok,
                                  max_prompt_len=8).input_ids
    out = finetune_value_model(
        val, pol, None, rule_reward, np.asarray(prompts), tok, mcfg,
        response_length=8, temperature=1.0, kl_coef=0.0, gamma=1.0,
        vcfg=ValueInitConfig(train_data_size=8, num_train_epochs=1,
                             per_device_train_batch_size=4),
    )
    assert "score" in out
