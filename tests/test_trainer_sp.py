"""Dense RLTrainer with sequence parallelism: chunked logprob scoring and
the jitted update run through ring attention when the mesh has sp > 1
(ROADMAP #7 remainder — SP for the non-sparse algorithms)."""

import json
import zlib

import numpy as np

import jax
import jax.numpy as jnp
import pytest

from nanorlhf_tpu.core import ModelConfig, init_params
from nanorlhf_tpu.data import ToyTokenizer, load_prompt_dataset
from nanorlhf_tpu.parallel import MeshConfig, make_mesh
from nanorlhf_tpu.trainer import AlgoName, RLConfig, RLTrainer


def det_reward(pmt_and_responses, eos_token):
    return np.asarray(
        [(zlib.crc32(s.encode()) % 17) / 17.0 for s in pmt_and_responses],
        np.float32,
    )


def _make_trainer(tmp_path, name, mesh, algo=AlgoName.GRPO, mcfg_replace=None,
                  **cfg_kw):
    import dataclasses

    tok = ToyTokenizer(512)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=512)
    if mcfg_replace:
        mcfg = dataclasses.replace(mcfg, **mcfg_replace)
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    dataset = load_prompt_dataset("synthetic:32", tok, max_prompt_len=16)
    defaults = dict(
        algo=algo,
        output_dir=str(tmp_path / name),
        response_length=8,
        temperature=1.0,
        sample_n=2 if algo in (AlgoName.GRPO, AlgoName.RLOO) else 1,
        kl_coef=0.05,
        total_episodes=4,
        per_device_train_batch_size=2,
        gradient_accumulation_steps=1,
        num_mini_batches=1,
        learning_rate=1e-3,
        use_lora=True, lora_r=4, lora_alpha=8,
        gradient_checkpointing=False,
        save_steps=0,
        report_to="jsonl",
        logging_steps=1,
    )
    cfg = RLConfig(**{**defaults, **cfg_kw})
    return RLTrainer(cfg, mcfg, tok, params, dataset, det_reward, mesh=mesh)


def _lora_leaves(trainer):
    return [np.asarray(x) for x in jax.tree.leaves(trainer.params["lora"])]


def _metric_rows(outdir):
    return [
        json.loads(l) for l in open(outdir / "metrics.jsonl")
        if "loss/policy_avg_new" in l
    ]


def test_dense_sp2_matches_single_device(tmp_path):
    devs = jax.devices()
    ctrl = _make_trainer(
        tmp_path, "ctrl", make_mesh(MeshConfig(1, 1, 1, 1), devices=devs[:1])
    )
    sp = _make_trainer(
        tmp_path, "sp2", make_mesh(MeshConfig(1, 1, 1, 2), devices=devs[:2])
    )
    assert sp._sp_on() and not ctrl._sp_on()
    # compare ONE update only: update 1 trains on bit-identical rollouts
    # (same PRNG stream + deterministic reward), so its metrics must agree.
    # Anything after update 1 samples from post-update params, where ring
    # attention's f32 reduction reorder shifts logits at bf16 scale and
    # categorical sampling amplifies near-ties into different tokens —
    # cross-parallelism trajectory equality is chaotic from update 2 on
    # (observed: a host change alone flipped it).
    s1 = ctrl.train(num_updates=1)
    s2 = sp.train(num_updates=1)
    assert s1["global_step"] == s2["global_step"] == 1

    # ring attention only reorders f32 reductions -> update-1 grads (and so
    # params) agree to bf16 slack
    for a, b in zip(_lora_leaves(ctrl), _lora_leaves(sp)):
        np.testing.assert_allclose(
            a.astype(np.float32), b.astype(np.float32), rtol=5e-3, atol=2e-3
        )

    m1 = _metric_rows(tmp_path / "ctrl")
    m2 = _metric_rows(tmp_path / "sp2")
    assert len(m1) == len(m2) == 1
    for a, b in zip(m1, m2):
        assert abs(a["loss/policy_avg_new"] - b["loss/policy_avg_new"]) < 1e-3
        assert abs(a["objective/kl_old"] - b["objective/kl_old"]) < 1e-3
        assert abs(a["eval_objective/scores_old"] - b["eval_objective/scores_old"]) < 1e-6
        # SP never materializes global logits — the entropy stat is a
        # per-shard mean pmean'd over the ring, and must match single-device
        assert abs(a["policy/entropy_avg_new"] - b["policy/entropy_avg_new"]) < 1e-3

    # a second sp update must still run and stay finite (no numeric claim)
    sp.train(num_updates=1)
    assert np.isfinite(_metric_rows(tmp_path / "sp2")[-1]["loss/policy_avg_new"])


def test_dense_sp_reinforce_trains(tmp_path):
    """Token-level PPO-clip path (REINFORCE) under sp=2 stays finite."""
    devs = jax.devices()
    tr = _make_trainer(
        tmp_path, "sp_reinf",
        make_mesh(MeshConfig(1, 1, 1, 2), devices=devs[:2]),
        algo=AlgoName.REINFORCE, advantage_whiten=True,
        # exercises the remat-through-shard_map path (sp + checkpointing)
        gradient_checkpointing=True,
    )
    tr.train(num_updates=1)
    m = _metric_rows(tmp_path / "sp_reinf")
    assert m and np.isfinite(m[-1]["loss/policy_avg_new"])


def test_dense_sp_capture_uses_sp_ref_scorer(tmp_path):
    """sampler_logprob_capture under sp: only the ref half of scoring runs,
    through the SP scorer; ratio-drift guard metric is emitted."""
    devs = jax.devices()
    tr = _make_trainer(
        tmp_path, "sp_cap",
        make_mesh(MeshConfig(1, 1, 1, 2), devices=devs[:2]),
        sampler_logprob_capture=True,
    )
    tr.train(num_updates=1)
    m = _metric_rows(tmp_path / "sp_cap")
    assert m and "sampler_capture/ratio_drift_new" in m[-1]
    assert np.isfinite(m[-1]["loss/policy_avg_new"])


def _ppo_fixtures(tmp_path, name, mesh):
    tok = ToyTokenizer(512)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=512)
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    vparams = init_params(mcfg, jax.random.PRNGKey(1), jnp.float32)
    vparams = {k: v for k, v in vparams.items() if k != "lm_head"}
    vparams["score"] = jnp.zeros((mcfg.hidden_size, 1), jnp.float32)
    dataset = load_prompt_dataset("synthetic:32", tok, max_prompt_len=16)
    cfg = RLConfig(
        algo=AlgoName.PPO,
        output_dir=str(tmp_path / name),
        response_length=8,
        temperature=1.0,
        total_episodes=2,
        per_device_train_batch_size=2,
        gradient_accumulation_steps=1,
        num_mini_batches=1,
        learning_rate=1e-3,
        use_lora=True, lora_r=4, lora_alpha=8,
        gradient_checkpointing=False,
        save_steps=0,
        report_to="jsonl",
        logging_steps=1,
    )
    return RLTrainer(cfg, mcfg, tok, params, dataset, det_reward,
                     value_params=vparams, mesh=mesh)


def test_ppo_sp2_matches_single_device(tmp_path):
    """PPO under sp=2: the value pass (rollout prediction AND the
    differentiated update forward) routes through sp_score_values; first
    update must match single-device metrics (identical rollouts, ring only
    reorders f32 reductions)."""
    devs = jax.devices()
    ctrl = _ppo_fixtures(tmp_path, "ppo_ctrl",
                         make_mesh(MeshConfig(1, 1, 1, 1), devices=devs[:1]))
    sp = _ppo_fixtures(tmp_path, "ppo_sp2",
                       make_mesh(MeshConfig(1, 1, 1, 2), devices=devs[:2]))
    assert sp._sp_on()
    s1 = ctrl.train(num_updates=1)
    s2 = sp.train(num_updates=1)
    assert s1["global_step"] == s2["global_step"] == 1
    m1 = _metric_rows(tmp_path / "ppo_ctrl")[0]
    m2 = _metric_rows(tmp_path / "ppo_sp2")[0]
    assert abs(m1["eval_objective/scores_old"] - m2["eval_objective/scores_old"]) < 1e-6
    for key in ("loss/policy_avg_new", "loss/value_avg_new", "objective/kl_old"):
        if key in m1:
            assert abs(m1[key] - m2[key]) < 2e-3, (key, m1[key], m2[key])


def test_sp_width_divisibility_enforced(tmp_path):
    """response_length not divisible by sp raises with a clear message."""
    devs = jax.devices()
    tr = _make_trainer(
        tmp_path, "sp_odd",
        make_mesh(MeshConfig(1, 1, 1, 2), devices=devs[:2]),
        response_length=7,
    )
    with pytest.raises(ValueError, match="divisible by sp"):
        tr.train(num_updates=1)


def test_dense_sp_flash_ring_update(tmp_path):
    """attention_impl="pallas" routes BOTH the scoring pass and the jitted
    update through the flash ring (`ring_attention_flash`, differentiable
    via its global-lse custom_vjp). Same kernels on both sides means the
    epoch-1 importance ratio is ~1 with ~zero variance — the
    kernel-consistency property (ADVICE r3; tolerance, not bitwise: the
    scoring and update programs are separately jitted and XLA may round
    their surrounding elementwise ops differently) — and the update must
    actually step the params."""
    devs = jax.devices()
    trainer = _make_trainer(
        tmp_path, "flashring",
        make_mesh(MeshConfig(1, 1, 1, 2), devices=devs[:2]),
        mcfg_replace={"attention_impl": "pallas"},
    )
    before = [x.copy() for x in _lora_leaves(trainer)]
    trainer.train(num_updates=1)
    after = _lora_leaves(trainer)
    assert any(not np.array_equal(a, b) for a, b in zip(before, after))

    rows = _metric_rows(tmp_path / "flashring")
    assert rows, "no update metrics logged"
    # single minibatch -> ratio_new IS the epoch-1 first-minibatch ratio,
    # the clean kernel-consistency signal (later minibatches would fold in
    # genuine update-induced drift; and ratio_var over one entry is 0 by
    # construction, so asserting it would be vacuous)
    assert abs(rows[0]["val/ratio_new"] - 1.0) < 1e-5
