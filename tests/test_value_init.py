"""Value initializer: regression on rollout returns actually reduces MSE."""

import jax
import jax.numpy as jnp
import numpy as np

from nanorlhf_tpu.core import ModelConfig, init_params, init_score_head, score_forward
from nanorlhf_tpu.data import ToyTokenizer, load_prompt_dataset
from nanorlhf_tpu.trainer.value_init import ValueInitConfig, finetune_value_model


def test_value_init_runs_and_learns():
    tok = ToyTokenizer(256)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=256)
    policy = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    ref = jax.tree.map(jnp.copy, policy)
    value = {k: v for k, v in policy.items() if k != "lm_head"}
    value = jax.tree.map(jnp.copy, value)
    value["score"] = init_score_head(mcfg, jax.random.PRNGKey(1), dtype=jnp.float32)

    def reward(prs, eos):
        return np.asarray([1.0 if eos in s else -0.5 for s in prs], np.float32)

    ds = load_prompt_dataset("synthetic:24", tok, max_prompt_len=10)
    before = jax.tree.leaves(value["score"])[0].copy()
    out = finetune_value_model(
        value, policy, ref, reward, np.asarray(ds.input_ids), tok, mcfg,
        response_length=6, temperature=1.0, kl_coef=0.05, gamma=1.0,
        vcfg=ValueInitConfig(train_data_size=24, num_train_epochs=2,
                             per_device_train_batch_size=4),
    )
    # params changed and remain finite
    assert not np.allclose(np.asarray(out["score"]), np.asarray(before))
    v = score_forward(out, mcfg, jnp.asarray(ds.input_ids[:2]), tok.pad_token_id)
    assert bool(jnp.all(jnp.isfinite(v)))


def test_value_init_lora_partition_freezes_backbone():
    """With value_lora_cfg the regression touches ONLY score + adapters +
    embed; the backbone (layers, norm) is bit-identical after training."""
    from nanorlhf_tpu.core.lora import LoraConfig, init_lora_params

    tok = ToyTokenizer(256)
    mcfg = ModelConfig.qwen2_tiny(vocab_size=256)
    policy = init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    ref = jax.tree.map(jnp.copy, policy)
    vcfg_lora = LoraConfig(r=4, alpha=8)
    value = jax.tree.map(
        jnp.copy, {k: v for k, v in policy.items() if k != "lm_head"}
    )
    value["score"] = init_score_head(mcfg, jax.random.PRNGKey(1), dtype=jnp.float32)
    value["lora"] = init_lora_params(mcfg, vcfg_lora, jax.random.PRNGKey(2),
                                     dtype=jnp.float32)

    def reward(prs, eos):
        return np.asarray([1.0 if eos in s else -0.5 for s in prs], np.float32)

    ds = load_prompt_dataset("synthetic:24", tok, max_prompt_len=10)
    backbone_before = [np.asarray(x).copy() for x in jax.tree.leaves(value["layers"])]
    score_before = np.asarray(value["score"]).copy()
    out = finetune_value_model(
        value, policy, ref, reward, np.asarray(ds.input_ids), tok, mcfg,
        response_length=6, temperature=1.0, kl_coef=0.05, gamma=1.0,
        vcfg=ValueInitConfig(train_data_size=24, num_train_epochs=2,
                             per_device_train_batch_size=4),
        value_lora_cfg=vcfg_lora,
    )
    for a, b in zip(backbone_before, jax.tree.leaves(out["layers"])):
        np.testing.assert_array_equal(a, np.asarray(b))  # frozen
    assert not np.allclose(np.asarray(out["score"]), score_before)  # trained
    assert any(
        float(jnp.abs(x).sum()) > 0 for x in jax.tree.leaves(out["lora"])
    )
