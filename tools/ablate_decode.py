"""Decode-lever ablation on real hardware — one process, one TPU claim.

Measures rollout (generation) throughput of the flagship-shaped policy under
each decode lever shipped in r2, at short and long response lengths. The
levers (see docs/ROADMAP.md #2):

  exact_topk    — lax.top_k k=64 pre-trim (full-vocab sort on TPU)
  approx_topk   — lax.approx_max_k pre-trim (default since r2)
  full_nucleus  — top_k=0 exact full-vocab nucleus (r1-zero default, r4)
  int8_weights  — rollout_quant="int8" weight-only base projections
  int8_kv       — kv_cache_quant="int8" + q8 decode kernel
  int8_both     — both quantizations
  compact4      — rollout_compaction_segments=4 (continuous-batching analogue)
  spec{2,4,8}   — speculative decode (sampler/speculative.py): n-gram draft
                  + batched k-token verify at spec_k ∈ {2,4,8}, nucleus
                  sampling (the spec_k=0 nucleus baseline IS approx_topk)
  greedy0       — greedy decode baseline (spec_k=0)
  greedy_spec{2,4,8} — greedy speculative decode; greedy accept is bit-exact
                  vs greedy0, so the sec_steady delta is pure mechanism cost
                  /win at the measured acceptance (printed per lever)
  n4_shared     — n=4 samples/prompt with shared-prompt-KV prefill (r5
                  default; vLLM prefix-sharing analogue)
  n4_repeat     — n=4 with the repeat-×N prefill (the pre-r5 path); the
                  sec_steady delta vs n4_shared is the measured prefill
                  dedup win at the GRPO operating point

Prints one JSON line per (lever, response_length) with decode tokens/s, and
a final summary line. Run ON the axon env (the only jax process):

  python tools/ablate_decode.py            # both lengths, all levers
  ABLATE_RESPONSE=2048 python tools/ablate_decode.py
  ABLATE_ROWS=32 ABLATE_LEVERS=approx_topk,int8_kv python tools/ablate_decode.py

Timings are end-to-end generate() walls (device sync via np.asarray fetch) —
per-op microbenches are unreliable over the tunnel; whole-loop walls are
honest (memory: chained dispatch + full fetch).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp

    from nanorlhf_tpu.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()  # warm-start repeat sessions (VERDICT r4 #2)

    from nanorlhf_tpu.core import ModelConfig, init_params
    from nanorlhf_tpu.core.quant import quantize_layers, rollout_view
    from nanorlhf_tpu.data import ToyTokenizer
    from nanorlhf_tpu.sampler import SamplingParams, generate

    rows = int(os.environ.get("ABLATE_ROWS", 32))
    lengths = (
        [int(os.environ.get("ABLATE_RESPONSE"))]
        if os.environ.get("ABLATE_RESPONSE")
        else [256, 2048]
    )
    lever_env = os.environ.get("ABLATE_LEVERS")
    model = os.environ.get("ABLATE_MODEL", "1_5b")

    mcfg = (
        ModelConfig.qwen2_1_5b() if model == "1_5b"
        else ModelConfig.qwen2_tiny(vocab_size=4096)
    )
    tok = ToyTokenizer(vocab_size=min(4096, mcfg.vocab_size))
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.bfloat16)
    dev = jax.devices()[0]
    print(f"[ablate] backend={jax.default_backend()} device={dev.device_kind}",
          file=sys.stderr)

    rng = np.random.default_rng(0)
    Tp = 64
    ids = rng.integers(4, tok.vocab_size, (rows, Tp)).astype(np.int32)
    ids[:, :8] = tok.pad_token_id  # a little left-padding
    ids_j = jnp.asarray(ids)
    mask_j = ids_j != tok.pad_token_id

    import dataclasses

    def make_levers():
        base = dict(params=params, mcfg=mcfg, sp_kw={}, note="")
        q_params = None
        kv_cfg = dataclasses.replace(mcfg, kv_cache_quant="int8")
        levers = {
            "exact_topk": dict(base, sp_kw={"approx_top_k": False}),
            "approx_topk": dict(base),
            # top_k=0: exact full-vocab nucleus (full sort) — the r1-zero
            # launcher default since r4 (base-model exploration must not be
            # top-k-truncated); its cost vs the k=64 pre-trim decides
            # whether other launchers follow
            "full_nucleus": dict(base, sp_kw={"top_k": 0}),
            "int8_weights": None,  # filled below (lazy quantize)
            "int8_kv": dict(base, mcfg=kv_cfg),
            "int8_both": None,
            "compact4": dict(base, sp_kw={"compaction_segments": 4}),
            "n4_shared": dict(base, sp_kw={"n": 4}),
            "n4_repeat": dict(base, sp_kw={"n": 4,
                                           "shared_prompt_prefill": False}),
            # speculative decode, spec_k x {greedy, nucleus} (ISSUE 5): the
            # spec_k=0 nucleus baseline is approx_topk above; greedy0 is the
            # greedy baseline. Acceptance on this random-prompt corpus is
            # the pessimistic floor — the roofline row in
            # docs/DECODE_ANALYSIS.md projects the repetitive-corpus case.
            "greedy0": dict(base, sp_kw={"greedy": True}),
        }
        for sk in (2, 4, 8):
            levers[f"spec{sk}"] = dict(base, sp_kw={"spec_k": sk})
            levers[f"greedy_spec{sk}"] = dict(
                base, sp_kw={"greedy": True, "spec_k": sk}
            )
        wanted = (lever_env.split(",") if lever_env else list(levers))
        if "int8_weights" in wanted or "int8_both" in wanted:
            q_params = rollout_view(params, quantize_layers(params["layers"]))
            levers["int8_weights"] = dict(base, params=q_params)
            levers["int8_both"] = dict(base, params=q_params, mcfg=kv_cfg)
        return {k: levers[k] for k in wanted if levers.get(k) is not None}

    results = {}
    for resp in lengths:
        for name, spec in make_levers().items():
            sp = SamplingParams(
                temperature=0.9, top_p=0.95, max_tokens=resp,
                **spec["sp_kw"],
            )
            # warmup (compile) + 2 timed reps
            times = []
            spec_stats: list = []
            for rep in range(3):
                t0 = time.time()
                out = generate(spec["params"], spec["mcfg"], ids_j, mask_j,
                               jax.random.PRNGKey(rep), sp,
                               eos_token_id=tok.eos_token_id,
                               pad_token_id=tok.pad_token_id,
                               spec_stats_out=spec_stats)
                np.asarray(out)  # full fetch = honest sync
                times.append(time.time() - t0)
            steady = float(np.mean(times[1:]))
            n_rows = out.shape[0]  # rows × n for the fanout levers
            toks = n_rows * resp / steady
            results[(name, resp)] = toks
            row = {
                "lever": name, "response_length": resp, "rows": n_rows,
                "sec_steady": round(steady, 3), "compile_sec": round(times[0], 1),
                "decode_tokens_per_sec": round(toks, 1),
            }
            if spec_stats:
                st = {k: int(np.asarray(v)) for k, v in spec_stats[-1].items()
                      if np.asarray(v).ndim == 0}  # accepted_rows is [B]
                row["spec_acceptance"] = round(
                    st["accepted"] / max(st["drafted"], 1), 4
                )
                row["spec_accepted_per_step"] = round(
                    st["emitted"] / max(st["row_steps"], 1), 3
                )
                row["spec_verify_steps"] = st["verify_steps"]
            print(json.dumps(row), flush=True)

    base_key = ("approx_topk", lengths[-1])
    # n4_* levers decode rows×4 physical rows — their raw tokens/s scales
    # with batch size, so they must not enter the cross-lever best/speedup
    # (which would crown them on a batch-size artifact). Their meaningful
    # number is the PAIRWISE shared-vs-repeat ratio, reported separately.
    # greedy* levers likewise: greedy decode skips the nucleus math the
    # headline pays, so they compare only within the greedy family (the
    # greedy_specK / greedy0 pairwise ratios below).
    same_batch = {k: v for k, v in results.items()
                  if not k[0].startswith(("n4_", "greedy"))}
    summary = {
        "metric": "decode_ablation",
        "device": dev.device_kind,
        "best": max(same_batch, key=same_batch.get) if same_batch else None,
        "tokens_per_sec": {f"{k[0]}@{k[1]}": round(v, 1)
                           for k, v in results.items()},
    }
    if base_key in same_batch:
        summary["speedup_vs_approx_topk"] = {
            f"{k[0]}@{k[1]}": round(v / results[base_key], 3)
            for k, v in same_batch.items() if k[1] == lengths[-1]
        }
    for resp in lengths:
        a, b = ("n4_shared", resp), ("n4_repeat", resp)
        if a in results and b in results:
            summary[f"n4_shared_speedup_vs_repeat@{resp}"] = round(
                results[a] / results[b], 3
            )
        for sk in (2, 4, 8):
            g, g0 = (f"greedy_spec{sk}", resp), ("greedy0", resp)
            if g in results and g0 in results:
                summary[f"greedy_spec{sk}_speedup@{resp}"] = round(
                    results[g] / results[g0], 3
                )
    print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
