"""Measure sampler-logprob-capture drift on real hardware (ROADMAP 5b).

`sampler_logprob_capture=True` reuses the sampler's per-token logprobs as the
rollout-policy logprobs, halving the scoring forwards. Decode-vs-scoring
numerics (KV-cache decode path vs the padded scoring forward, bf16) make the
epoch-1 importance ratio deviate from exactly 1; the trainer logs that
residual as `sampler_capture/ratio_drift_new` = mean |exp(score_lp −
captured_lp) − 1| over response tokens. This harness runs a few flagship-
shaped updates with capture ON and reports the measured drift so the default
can be flipped (or the reason not to recorded) — VERDICT r3 #7.

Run ON the axon env (the only jax process). Env knobs: DRIFT_UPDATES (2),
DRIFT_RESPONSE (256), DRIFT_PROMPTS (16), DRIFT_MODEL (1_5b | tiny).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from nanorlhf_tpu.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()  # warm-start repeat sessions (VERDICT r4 #2)

    from nanorlhf_tpu.core import ModelConfig, init_params
    from nanorlhf_tpu.data import ToyTokenizer, load_prompt_dataset
    from nanorlhf_tpu.parallel import MeshConfig
    from nanorlhf_tpu.trainer import AlgoName, RLConfig, RLTrainer

    updates = int(os.environ.get("DRIFT_UPDATES", 2))
    resp = int(os.environ.get("DRIFT_RESPONSE", 256))
    prompts = int(os.environ.get("DRIFT_PROMPTS", 16))
    model = os.environ.get("DRIFT_MODEL", "1_5b")

    mcfg = (ModelConfig.qwen2_1_5b() if model == "1_5b"
            else ModelConfig.qwen2_tiny(vocab_size=4096))
    tok = ToyTokenizer(vocab_size=min(4096, mcfg.vocab_size))
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.bfloat16)
    ds = load_prompt_dataset(f"synthetic:{prompts * 2}", tok, max_prompt_len=64)

    def reward(p, eos):
        return np.asarray([1.0 if eos in s else 0.0 for s in p], np.float32)

    run_dir = "/tmp/nanorlhf_capture_drift"
    cfg = RLConfig(
        algo=AlgoName.GRPO, output_dir=run_dir, response_length=resp,
        temperature=0.9, sample_n=4, per_device_train_batch_size=prompts,
        gradient_accumulation_steps=1, num_mini_batches=1,
        total_episodes=updates * prompts * 4, use_lora=True,
        gradient_checkpointing=True, mesh=MeshConfig(1, 1, 1), save_steps=0,
        report_to="jsonl", logging_steps=1,
        sampler_logprob_capture=True,
    )
    t = RLTrainer(cfg, mcfg, tok, params, ds, reward)
    t.train(num_updates=updates)

    rows = [json.loads(l) for l in open(os.path.join(run_dir, "metrics.jsonl"))]
    drifts = [r["sampler_capture/ratio_drift_new"] for r in rows
              if "sampler_capture/ratio_drift_new" in r]
    print(json.dumps({
        "metric": "sampler_capture_ratio_drift",
        "backend": jax.default_backend(),
        "device": jax.devices()[0].device_kind,
        "model": model, "response_length": resp,
        "per_update": [round(d, 6) for d in drifts],
        "mean": round(float(np.mean(drifts)), 6) if drifts else None,
        "max": round(float(np.max(drifts)), 6) if drifts else None,
    }), flush=True)


if __name__ == "__main__":
    main()
