"""Run inspector — the query side of the sample lineage ledger.

Joins a run's provenance stream (`<run>/lineage/ledger_*.jsonl`, written by
telemetry/lineage.py) back into per-sample stories: which worker and lease
produced rollout K, what the grader scored it, how stale it was at
consumption, and why any row left the batch. Works from the ledger ALONE —
no live trainer, no metrics.jsonl required (though `--worst` will read
scores from `sample`/`reward` events the ledger already carries).

  python tools/inspect_run.py RUN_DIR                 # run overview
  python tools/inspect_run.py RUN_DIR --drops         # drop-reason histogram
  python tools/inspect_run.py RUN_DIR --worst 5       # N worst-reward samples,
                                                      # full text + timeline
  python tools/inspect_run.py RUN_DIR --index 42      # one rollout's chain:
                                                      # lease→generation→queue
                                                      # →reward→outcome
  python tools/inspect_run.py RUN_DIR --drops --json  # machine-readable out
  python tools/inspect_run.py RUN_DIR --latency       # queue-wait + generation
                                                      # percentiles from the
                                                      # ledger alone
  python tools/inspect_run.py RUN_DIR --turns         # per-episode turn
                                                      # timelines (multi-turn
                                                      # env runs): turn count,
                                                      # tool wall, observation
                                                      # lengths, per-turn
                                                      # reward
  python tools/inspect_run.py RUN_DIR --segments      # per-sample weight-
                                                      # version segment
                                                      # timelines (in-flight
                                                      # swap runs): spans,
                                                      # swaps/sample, tokens
                                                      # per policy version,
                                                      # install wait, joined
                                                      # to `turn` spans
  python tools/inspect_run.py statusz.json --serving  # serving engine +
                                                      # radix prefix-cache
                                                      # sections of a saved
                                                      # /statusz snapshot
  python tools/inspect_run.py RUN_DIR --traffic       # offered-load/goodput/
                                                      # shed timeline + auto-
                                                      # scale decisions from
                                                      # `traffic`/`autoscale`
                                                      # events alone
  python tools/inspect_run.py RUN_DIR --chaos         # chaos soak replay:
                                                      # composed spec, fault-
                                                      # fire timeline, and
                                                      # journaled auditor
                                                      # verdicts from
                                                      # `chaos_run`/`fault`/
                                                      # `chaos_audit` events

RUN_DIR is the trainer's output_dir (containing `lineage/`) or the lineage
directory itself; for --serving it is a saved /statusz JSON (curl the
gateway's or trainer's /statusz into a file), or a directory containing
`statusz.json`. jax-free: runs anywhere the JSONL files can be read.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from nanorlhf_tpu.telemetry.hist import (  # noqa: E402
    percentiles_from_samples,
)
from nanorlhf_tpu.telemetry.lineage import (  # noqa: E402
    chains,
    drop_histogram,
    read_ledger,
)


def latency_report(events) -> dict:
    """Reconstruct latency percentiles from the ledger ALONE: queue wait
    from each `queue` event's dequeue_t − enqueue_t (both on the producer's
    monotonic clock) and generation duration from each `generation` event's
    gen_s. Summaries use the same percentile definition the live
    LatencyHub cross-checks against (hist.percentiles_from_samples), so a
    live run's `latency/queue_wait_s` / `latency/generation_s` histograms
    and this offline view disagree by at most one histogram bucket."""
    queue_waits = [
        ev["dequeue_t"] - ev["enqueue_t"]
        for ev in events
        if ev.get("type") == "queue"
        and isinstance(ev.get("dequeue_t"), (int, float))
        and isinstance(ev.get("enqueue_t"), (int, float))
        and ev["enqueue_t"] > 0.0
    ]
    gen_s = [
        ev["gen_s"] for ev in events
        if ev.get("type") == "generation"
        and isinstance(ev.get("gen_s"), (int, float))
    ]
    return {
        "queue_wait_s": percentiles_from_samples(queue_waits),
        "generation_s": percentiles_from_samples(gen_s),
    }


def turns_report(events) -> dict:
    """Reconstruct per-episode turn timelines from `turn` events ALONE —
    the offline mirror of the live `env/*` metric rows (docs/METRICS.md).
    One entry per (rollout_index, row) episode: turn count, summed tool
    wall, observation token lengths, per-turn rewards, and each turn's
    model-token range; `turns_per_episode` cross-checks the live metric."""
    episodes: dict = {}
    for ev in events:
        if ev.get("type") != "turn":
            continue
        key = (ev.get("rollout_index"), ev.get("row"))
        episodes.setdefault(key, []).append(ev)
    out = []
    for (idx, row), evs in sorted(episodes.items(),
                                  key=lambda kv: (kv[0][0] or 0,
                                                  kv[0][1] or 0)):
        evs.sort(key=lambda e: e.get("turn", 0))
        out.append({
            "rollout_index": idx,
            "row": row,
            "turns": len(evs),
            "tool_wall_s": round(
                sum(e.get("tool_wall_s") or 0.0 for e in evs), 6),
            "obs_tokens": [int(e.get("obs_tokens") or 0) for e in evs],
            "rewards": [e.get("reward") for e in evs],
            "tok_ranges": [e.get("tok_range") for e in evs],
        })
    tpe = (sum(e["turns"] for e in out) / len(out)) if out else 0.0
    return {"episodes": out, "turns_per_episode": tpe}


def segments_report(events) -> dict:
    """Reconstruct per-sample weight-version timelines from `generation`
    events' `segments` lists ALONE (docs/ORCHESTRATOR.md §in-flight
    swaps) — the offline mirror of `rollout/segments_per_sample` /
    `rollout/swap_installs`. One entry per (rollout_index, row) sample:
    its ordered `{policy_version, tok_range}` spans, swap count
    (len(segments) − 1), and the row's version spread. `tok_range` is in
    response-token coordinates — the SAME space as multi-turn `turn`
    events' spans, so each sample also carries the turns that overlap
    it when the run was multi-turn. Aggregates: segments/sample, total
    swaps, tokens decoded under each policy version (spans with an
    unknown end — the no-swap default stamp — are excluded from token
    totals), and swap-install latency from `swap_wait_s` when the
    payload carried it."""
    turns: dict = {}
    for ev in events:
        if ev.get("type") == "turn":
            turns.setdefault((ev.get("rollout_index"), ev.get("row")),
                             []).append(ev)
    samples: dict = {}
    waits = []
    for ev in events:
        if ev.get("type") != "generation":
            continue
        if isinstance(ev.get("swap_wait_s"), (int, float)):
            waits.append(float(ev["swap_wait_s"]))
        for seg in ev.get("segments") or []:
            key = (ev.get("rollout_index"), seg.get("row"))
            samples.setdefault(key, []).append(seg)
    out, tokens_by_version = [], {}
    for (idx, row), segs in sorted(
            samples.items(),
            key=lambda kv: (kv[0][0] or 0, kv[0][1] or 0)):
        segs.sort(key=lambda s: (s.get("tok_range") or [0, 0])[0])
        versions = [s.get("policy_version") for s in segs
                    if s.get("policy_version") is not None]
        for s in segs:
            lo, hi = (s.get("tok_range") or [None, None])
            if (s.get("policy_version") is not None
                    and isinstance(lo, int) and isinstance(hi, int)):
                tokens_by_version[s["policy_version"]] = (
                    tokens_by_version.get(s["policy_version"], 0)
                    + max(0, hi - lo))
        entry = {
            "rollout_index": idx,
            "row": row,
            "segments": [{"policy_version": s.get("policy_version"),
                          "tok_range": s.get("tok_range")} for s in segs],
            "swaps": max(0, len(segs) - 1),
            "version_spread": (max(versions) - min(versions)
                               if versions else 0),
        }
        tevs = turns.get((idx, row))
        if tevs:
            entry["turn_tok_ranges"] = [
                e.get("tok_range")
                for e in sorted(tevs, key=lambda e: e.get("turn", 0))]
        out.append(entry)
    n = len(out)
    return {
        "samples": out,
        "segments_per_sample": (
            sum(len(s["segments"]) for s in out) / n if n else 0.0),
        "swaps_total": sum(s["swaps"] for s in out),
        "rows_multi_segment": sum(1 for s in out if s["swaps"] > 0),
        "tokens_by_version": {
            str(v): t for v, t in sorted(tokens_by_version.items())},
        "swap_wait_s": percentiles_from_samples(waits) if waits else None,
    }


def _print_segments(rep: dict) -> None:
    smp = rep["samples"]
    if not smp:
        print("no `generation` events with segments in the ledger "
              "(lineage off, or a pre-swap-era run)")
        return
    print(f"{len(smp)} samples, "
          f"{rep['segments_per_sample']:.2f} segments/sample, "
          f"{rep['swaps_total']} swaps "
          f"({rep['rows_multi_segment']} multi-segment rows)")
    if rep["tokens_by_version"]:
        tv = ", ".join(f"v{v}: {t}"
                       for v, t in rep["tokens_by_version"].items())
        print(f"  tokens by policy version: {tv}")
    if rep["swap_wait_s"] and rep["swap_wait_s"].get("count"):
        p = rep["swap_wait_s"]
        print(f"  swap install wait: p50 {p['p50_s']:.4f}s "
              f"p95 {p['p95_s']:.4f}s over {p['count']} rollouts")
    for s in smp:
        spans = ", ".join(
            f"v{g['policy_version']}@{g['tok_range']}"
            for g in s["segments"])
        line = (f"  rollout {s['rollout_index']} row {s['row']}: "
                f"{len(s['segments'])} seg [{spans}]")
        if s.get("turn_tok_ranges"):
            line += f" turns {s['turn_tok_ranges']}"
        print(line)


def traffic_report(events) -> dict:
    """Reconstruct a loadgen run from the ledger ALONE (docs/TRAFFIC.md):
    per-outcome counts and shed reasons from `traffic` events, offered/
    goodput rates over the spec's arrival span, client-TTFT percentiles
    through the same digest the live hub cross-checks against
    (hist.percentiles_from_samples), a per-second offered/completed/shed
    timeline binned on each request's deterministic `t_offset`, and the
    autoscaler's decision list from `autoscale` events."""
    fired = [ev for ev in events if ev.get("type") == "traffic"]
    runs = [ev for ev in events if ev.get("type") == "traffic_run"]
    scales = [ev for ev in events if ev.get("type") == "autoscale"]
    outcomes: dict = {}
    reasons: dict = {}
    timeline: dict = {}
    ttfts = []
    max_off = 0.0
    for ev in fired:
        out = ev.get("outcome") or "unknown"
        outcomes[out] = outcomes.get(out, 0) + 1
        if out == "shed":
            r = ev.get("reason") or "unknown"
            reasons[r] = reasons.get(r, 0) + 1
        if isinstance(ev.get("ttft_s"), (int, float)):
            ttfts.append(ev["ttft_s"])
        off = ev.get("t_offset")
        if isinstance(off, (int, float)):
            max_off = max(max_off, off)
            sec = int(off)
            bin_ = timeline.setdefault(
                sec, {"offered": 0, "completed": 0, "shed": 0, "errors": 0})
            bin_["offered"] += 1
            bin_[out if out in bin_ else "errors"] += 1
    n = len(fired)
    completed = outcomes.get("completed", 0)
    span = max_off if max_off > 0 else None
    return {
        "runs": [{k: v for k, v in ev.items()
                  if k in ("spec_digest", "n_requests", "rate_rps",
                           "arrival", "seed", "mode", "time_scale",
                           "key_path")}
                 for ev in runs],
        "offered": n,
        "outcomes": outcomes,
        "shed_reasons": reasons,
        "offered_rps": round(n / span, 4) if span else None,
        "goodput_rps": round(completed / span, 4) if span else None,
        "shed_frac": round(outcomes.get("shed", 0) / n, 4) if n else None,
        "client_ttft_s": percentiles_from_samples(ttfts),
        "timeline": [{"second": s, **timeline[s]}
                     for s in sorted(timeline)],
        "autoscale": [{k: ev.get(k)
                       for k in ("action", "worker_id", "workers_before",
                                 "workers_after", "level", "queue_depth",
                                 "eval")}
                      for ev in scales],
    }


def _print_traffic(rep: dict) -> None:
    for run in rep["runs"]:
        print(f"workload: {run.get('n_requests')} requests @ "
              f"{run.get('rate_rps')} rps ({run.get('arrival')}), "
              f"seed {run.get('seed')}, spec {run.get('spec_digest')}, "
              f"mode {run.get('mode')}")
    n = rep["offered"]
    if not n:
        print("no `traffic` events in the ledger (loadgen never ran, or "
              "lineage was off)")
        return
    print(f"{n} requests: {rep['outcomes']}")
    if rep["shed_reasons"]:
        print("shed reasons:")
        for r, c in sorted(rep["shed_reasons"].items(),
                           key=lambda kv: -kv[1]):
            print(f"  {r:<16s} {c}")
    if rep["offered_rps"] is not None:
        print(f"offered {rep['offered_rps']:.2f} rps, goodput "
              f"{rep['goodput_rps']:.2f} rps, shed "
              f"{100.0 * rep['shed_frac']:.1f}% (over the spec's arrival "
              f"span — unscaled t_offset seconds)")
    t = rep["client_ttft_s"]
    if t["count"]:
        print(f"client TTFT: n={t['count']} p50={t['p50_s']:.4f}s "
              f"p95={t['p95_s']:.4f}s p99={t['p99_s']:.4f}s "
              f"max={t['max_s']:.4f}s")
    if rep["timeline"]:
        print("per-second timeline (by spec arrival offset):")
        for b in rep["timeline"]:
            print(f"  t+{b['second']:<4d} offered {b['offered']:<4d} "
                  f"completed {b['completed']:<4d} shed {b['shed']:<4d} "
                  f"errors {b['errors']}")
    if rep["autoscale"]:
        print("autoscale decisions:")
        for d in rep["autoscale"]:
            print(f"  eval {d.get('eval'):<4} {d.get('action'):<10s} "
                  f"worker {d.get('worker_id')} "
                  f"({d.get('workers_before')}->{d.get('workers_after')} "
                  f"workers), level {d.get('level')}, queue "
                  f"{d.get('queue_depth')}")


def chaos_report(events) -> dict:
    """Rebuild a chaos soak's story from the ledger ALONE (docs/
    RESILIENCE.md §chaos): the `chaos_run` header (seed + spec + key
    path — the complete replay recipe), the fault-fire timeline in
    soak-relative order, per-site fire counts, and the `chaos_audit`
    verdicts the runner journaled after teardown."""
    runs = [ev for ev in events if ev.get("type") == "chaos_run"]
    fires = [ev for ev in events if ev.get("type") == "fault"]
    audits = [ev for ev in events if ev.get("type") == "chaos_audit"]
    per_site: dict = {}
    for ev in fires:
        p = ev.get("point") or "unknown"
        per_site[p] = per_site.get(p, 0) + 1
    return {
        "runs": [{k: ev.get(k)
                  for k in ("seed", "spec", "spec_digest", "path",
                            "key_path")}
                 for ev in runs],
        "fires": [{k: ev.get(k)
                   for k in ("point", "worker", "action", "t_offset")}
                  for ev in fires],
        "fires_by_site": per_site,
        "audits": [{k: ev.get(k)
                    for k in ("name", "ok", "detail", "checked")}
                   for ev in audits],
        "ok": (all(a.get("ok") for a in audits) if audits else None),
    }


def _print_chaos(rep: dict) -> None:
    if not rep["runs"] and not rep["audits"]:
        print("no `chaos_run`/`chaos_audit` events in the ledger (not a "
              "chaos soak, or lineage was off)")
        return
    for run in rep["runs"]:
        print(f"chaos run: path={run.get('path')} seed={run.get('seed')} "
              f"digest={run.get('spec_digest')}")
        print(f"  spec: {run.get('spec') or '(empty)'}")
        print(f"  key path: {run.get('key_path')}")
    if rep["fires"]:
        print(f"{len(rep['fires'])} fault fires:")
        for f in rep["fires"]:
            t = f.get("t_offset")
            stamp = f"+{t:8.3f}s" if isinstance(t, (int, float)) else " " * 10
            who = (f" worker {f['worker']}"
                   if f.get("worker") is not None else "")
            print(f"  {stamp}  {f.get('point'):<22s} "
                  f"{f.get('action')}{who}")
    else:
        print("no fault fires recorded")
    if rep["audits"]:
        print("auditor verdicts:")
        for a in rep["audits"]:
            mark = "ok " if a.get("ok") else "FAIL"
            extra = f" — {a['detail']}" if a.get("detail") else ""
            print(f"  [{mark}] {a.get('name')} "
                  f"(checked={a.get('checked')}){extra}")
        print("verdict:", "PASS" if rep["ok"] else "FAIL")
    else:
        print("no journaled auditor verdicts (soak crashed before the "
              "audit pass?)")


def serving_report(path: str) -> dict:
    """Load a saved /statusz snapshot and pull out the serving engine and
    radix prefix-cache sections. Accepts either shape: the gateway's
    /statusz (the engine snapshot itself, with a nested `prefix_cache`)
    or the trainer's /statusz (whose top-level `prefix_cache` is the
    radix snapshot when `rollout_prefix_cache` is on)."""
    if os.path.isdir(path):
        path = os.path.join(path, "statusz.json")
    with open(path) as f:
        snap = json.load(f)
    if isinstance(snap.get("counters"), dict):         # gateway /statusz
        cache = snap.get("prefix_cache")
        engine = {k: v for k, v in snap.items()
                  if k not in ("prefix_cache", "session")}
        return {"engine": engine, "prefix_cache": cache,
                "session": snap.get("session")}
    return {"engine": None, "prefix_cache": snap.get("prefix_cache"),
            "session": snap.get("session")}


def _print_serving(rep: dict) -> None:
    eng = rep["engine"]
    if eng is not None:
        print("serving engine:")
        for k in ("rows", "active", "pending", "prompt_len",
                  "max_new_tokens", "page_size", "num_pages",
                  "prefill_token_dispatch"):
            if k in eng:
                print(f"  {k:<24s} {eng[k]}")
        for k, v in sorted((eng.get("counters") or {}).items()):
            print(f"  counters.{k:<15s} {v}")
        slo = eng.get("slo") or {}
        if slo:
            print(f"  shed rule: {slo.get('rule')} "
                  f"p{int(100 * slo.get('quantile', 0.95))} "
                  f"> {slo.get('warn_s')}s after "
                  f"{slo.get('warmup')} samples")
    sess = rep.get("session")
    if sess is not None:
        # the decode session (sampler/paged/session.py status()): resident
        # rows + per-row feature flags, the chunked-prefill backlog, and
        # the dispatch counters the spec×prefix A/B gates read
        print("decode session:")
        print(f"  {'mode':<24s} {sess.get('mode')}")
        print(f"  {'rows':<24s} {sess.get('live_rows')}/{sess.get('rows')}"
              " live")
        feats = sess.get("features") or {}
        on = [k if v is True else f"{k}={v}"
              for k, v in sorted(feats.items()) if v]
        print(f"  {'features':<24s} {', '.join(on) if on else '(none)'}")
        pend = sess.get("pending_prefill") or {}
        print(f"  {'prefill backlog':<24s} rows={pend.get('rows')} "
              f"tokens={pend.get('backlog_tokens')}")
        for k, v in sorted((sess.get("counters") or {}).items()):
            print(f"  counters.{k:<24s} {v}")
        for i, rf in enumerate(sess.get("row_flags") or []):
            flags = [k if v is True else f"{k}={v}"
                     for k, v in sorted(rf.items()) if v]
            print(f"  row[{i}]: {', '.join(flags) if flags else 'idle'}")
    cache = rep["prefix_cache"]
    if cache is None:
        print("prefix cache: (absent — rollout_prefix_cache off, or "
              "snapshot predates it)")
        return
    print("radix prefix cache:")
    for k in ("nodes", "cached_pages", "free_pages", "num_pages",
              "shared_pages", "page_size", "lookups", "lookup_tokens",
              "hit_tokens", "hit_frac", "cow_splits", "evicted_pages",
              "shared_pages_acquired", "inserted_nodes"):
        if k in cache:
            v = cache[k]
            v = f"{v:.4f}" if isinstance(v, float) else v
            print(f"  {k:<24s} {v}")


def _fmt_time(ev, t0):
    t = ev.get("time")
    return f"+{t - t0:8.3f}s" if isinstance(t, (int, float)) else " " * 10


def _chain_timeline(idx, by_type, t0):
    """Render one rollout index's event chain in wall-clock order."""
    lines = [f"rollout {idx}:"]
    evs = sorted(
        (ev for evl in by_type.values() for ev in evl),
        key=lambda e: e.get("time", 0.0),
    )
    for ev in evs:
        etype = ev["type"]
        detail = ""
        if etype == "lease":
            who = f"worker {ev.get('worker_id')}"
            if ev.get("reassigned_from") is not None:
                who += f" (reassigned from worker {ev['reassigned_from']})"
            detail = (f"lease {ev.get('lease_id')} -> {who}, "
                      f"cursor {ev.get('cursor')}")
            if ev.get("key_path"):
                detail += f", key {ev['key_path']}"
        elif etype == "generation":
            detail = (f"policy v{ev.get('policy_version')} on worker "
                      f"{ev.get('worker_id')}")
            if ev.get("gen_s") is not None:
                detail += f", {ev['gen_s']:.2f}s"
            spec = ev.get("spec")
            if spec:
                detail += (f", spec acceptance "
                           f"{spec.get('acceptance', '?')}")
        elif etype == "queue":
            wait = None
            if ev.get("dequeue_t") and ev.get("enqueue_t"):
                wait = ev["dequeue_t"] - ev["enqueue_t"]
            detail = f"staleness {ev.get('staleness')}"
            if wait is not None:
                detail += f", queued {wait:.2f}s"
        elif etype == "reward":
            scores = ev.get("scores") or []
            detail = (f"{len(scores)} scores, mean "
                      f"{sum(scores) / max(len(scores), 1):.4f}, "
                      f"attempt {ev.get('attempt')}, "
                      f"grader {ev.get('wall_s', 0):.2f}s")
        elif etype == "outcome":
            detail = (f"step {ev.get('step')}: kept {ev.get('kept')} rows, "
                      f"mean advantage {ev.get('advantage')}")
        elif etype == "drop":
            detail = f"DROP [{ev.get('reason')}] x{ev.get('count', 1)}"
            if ev.get("row") is not None:
                detail += f" (row {ev['row']})"
        elif etype == "sample":
            detail = (f"row {ev.get('row')} score {ev.get('score')} "
                      f"({len(ev.get('response', ''))} chars)")
        elif etype == "turn":
            detail = (f"row {ev.get('row')} turn {ev.get('turn')}: "
                      f"tokens {ev.get('tok_range')}, "
                      f"reward {ev.get('reward')}, "
                      f"tool {ev.get('tool_wall_s', 0) or 0:.3f}s")
            if ev.get("obs_range"):
                detail += (f", obs {ev['obs_range']} "
                           f"({ev.get('obs_tokens')} tokens)")
        lines.append(f"  {_fmt_time(ev, t0)}  {etype:<10s} {detail}")
    return "\n".join(lines)


def _sample_rows(events):
    """Per-row (index, row, score, query, response) from `sample` events —
    the full-text records the trainer routes to the ledger (satellite 1);
    falls back to per-score rows from `reward` events when a run logged no
    sample text."""
    rows = []
    seen_text = False
    for ev in events:
        if ev.get("type") == "sample":
            seen_text = True
            rows.append({
                "rollout_index": ev.get("rollout_index"),
                "row": ev.get("row"),
                "score": ev.get("score"),
                "query": ev.get("query", ""),
                "response": ev.get("response", ""),
            })
    if not seen_text:
        for ev in events:
            if ev.get("type") == "reward":
                for i, s in enumerate(ev.get("scores") or []):
                    rows.append({
                        "rollout_index": ev.get("rollout_index"),
                        "row": i, "score": s, "query": "", "response": "",
                    })
    return rows


def main():
    ap = argparse.ArgumentParser(
        description="inspect a run's sample-lineage ledger"
    )
    ap.add_argument("run_dir", help="run output dir (or its lineage/ dir)")
    ap.add_argument("--drops", action="store_true",
                    help="drop-reason histogram (samples per reason)")
    ap.add_argument("--worst", type=int, metavar="N", default=0,
                    help="N worst-reward samples with text + timeline")
    ap.add_argument("--index", type=int, default=None,
                    help="full event chain for one rollout index")
    ap.add_argument("--latency", action="store_true",
                    help="queue-wait + generation percentiles reconstructed "
                         "from the ledger (no live trainer needed)")
    ap.add_argument("--turns", action="store_true",
                    help="per-episode turn timelines from `turn` events "
                         "(multi-turn env runs): turn count, tool wall, "
                         "observation lengths, per-turn reward")
    ap.add_argument("--segments", action="store_true",
                    help="per-sample weight-version segment timelines from "
                         "`generation` events' segments lists (in-flight "
                         "swap runs), joined to `turn` events on the shared "
                         "response-token coordinates")
    ap.add_argument("--traffic", action="store_true",
                    help="offered-load/goodput/shed timeline + autoscale "
                         "decisions reconstructed from `traffic`/"
                         "`autoscale` events alone (docs/TRAFFIC.md)")
    ap.add_argument("--chaos", action="store_true",
                    help="chaos soak replay: composed spec, fault-fire "
                         "timeline, and journaled auditor verdicts from "
                         "`chaos_run`/`fault`/`chaos_audit` events alone "
                         "(docs/RESILIENCE.md §chaos)")
    ap.add_argument("--serving", action="store_true",
                    help="serving engine + radix prefix-cache sections of "
                         "a saved /statusz snapshot (run_dir is the JSON "
                         "file, or a dir containing statusz.json)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args()

    if args.serving:
        try:
            rep = serving_report(args.run_dir)
        except (OSError, json.JSONDecodeError) as e:
            print(f"cannot read a /statusz snapshot from "
                  f"{args.run_dir}: {e}", file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(rep, sort_keys=True, default=str))
        else:
            _print_serving(rep)
        return 0

    events = list(read_ledger(args.run_dir))
    if not events:
        print(f"no ledger events under {args.run_dir} "
              f"(is cfg.lineage on?)", file=sys.stderr)
        return 1
    by_index = chains(events)
    t0 = min(ev.get("time", float("inf")) for ev in events)

    if args.drops:
        hist = drop_histogram(events)
        if args.json:
            print(json.dumps({"drops": hist}, sort_keys=True))
        else:
            print("drop-reason histogram (samples):")
            for reason, count in sorted(
                    hist.items(), key=lambda kv: -kv[1]):
                print(f"  {reason:<24s} {count}")
            if not hist:
                print("  (no drops recorded)")
        return 0

    if args.latency:
        rep = latency_report(events)
        if args.json:
            print(json.dumps({"latency": rep}, sort_keys=True))
            return 0
        print("latency percentiles (reconstructed from the ledger):")
        for name, summ in sorted(rep.items()):
            if not summ["count"]:
                print(f"  {name:<16s} (no events)")
                continue
            print(f"  {name:<16s} n={summ['count']:<6d} "
                  f"p50={summ['p50_s']:.4f}s p95={summ['p95_s']:.4f}s "
                  f"p99={summ['p99_s']:.4f}s "
                  f"mean={summ['mean_s']:.4f}s max={summ['max_s']:.4f}s")
        return 0

    if args.segments:
        rep = segments_report(events)
        if args.json:
            print(json.dumps(rep, sort_keys=True))
            return 0
        _print_segments(rep)
        return 0

    if args.traffic:
        rep = traffic_report(events)
        if args.json:
            print(json.dumps(rep, sort_keys=True))
            return 0
        _print_traffic(rep)
        return 0

    if args.chaos:
        rep = chaos_report(events)
        if args.json:
            print(json.dumps(rep, sort_keys=True))
            return 0
        _print_chaos(rep)
        return 0

    if args.turns:
        rep = turns_report(events)
        if args.json:
            print(json.dumps(rep, sort_keys=True))
            return 0
        eps = rep["episodes"]
        if not eps:
            print("no `turn` events in the ledger (single-turn run, or "
                  "env_max_turns == 1)")
            return 0
        print(f"{len(eps)} episodes, "
              f"{rep['turns_per_episode']:.2f} turns/episode")
        for e in eps:
            rewards = ", ".join(
                "?" if r is None else f"{r:.3f}" for r in e["rewards"])
            obs = ", ".join(str(o) for o in e["obs_tokens"])
            print(f"  rollout {e['rollout_index']} row {e['row']}: "
                  f"{e['turns']} turns, tool {e['tool_wall_s']:.3f}s, "
                  f"obs tokens [{obs}], rewards [{rewards}]")
        return 0

    if args.index is not None:
        by_type = by_index.get(args.index)
        if by_type is None:
            print(f"rollout index {args.index} not in ledger "
                  f"(sampled out, or never consumed)", file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(
                {t: evs for t, evs in sorted(by_type.items())},
                sort_keys=True,
            ))
        else:
            print(_chain_timeline(args.index, by_type, t0))
        return 0

    if args.worst:
        rows = [r for r in _sample_rows(events) if r["score"] is not None]
        rows.sort(key=lambda r: r["score"])
        rows = rows[: args.worst]
        if args.json:
            print(json.dumps({"worst": rows}))
            return 0
        for r in rows:
            print("=" * 70)
            print(f"rollout {r['rollout_index']} row {r['row']}  "
                  f"score {r['score']}")
            if r["query"]:
                print(f"--- query ---\n{r['query']}")
            if r["response"]:
                print(f"--- response ---\n{r['response']}")
            by_type = by_index.get(r["rollout_index"])
            if by_type:
                print("--- timeline ---")
                print(_chain_timeline(r["rollout_index"], by_type, t0))
        return 0

    # default: run overview
    n_by_type: dict = {}
    for ev in events:
        n_by_type[ev["type"]] = n_by_type.get(ev["type"], 0) + 1
    hist = drop_histogram(events)
    overview = {
        "events": len(events),
        "rollout_indices": len(by_index),
        "by_type": n_by_type,
        "drops": hist,
    }
    if args.json:
        print(json.dumps(overview, sort_keys=True))
        return 0
    print(f"{len(events)} events across {len(by_index)} rollout indices")
    for t, c in sorted(n_by_type.items()):
        print(f"  {t:<10s} {c}")
    if hist:
        print("drops:")
        for reason, count in sorted(hist.items(), key=lambda kv: -kv[1]):
            print(f"  {reason:<24s} {count}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
