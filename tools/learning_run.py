"""Silicon learning-curve artifact: sparse GRPO (the r1-zero path) climbing a
shaped math-format reward from scratch.

The reference's learning evidence is a rising reward curve
(`/root/reference/README.md:36-37`, `docs/perf.png`) and MATH-500 accuracy
improving from a base model (`examples/r1-v0/README.md:9-14`). This
environment has zero egress and no pretrained checkpoint on disk, so a binary
boxed-answer reward on a random-init policy would be flat (no gradient
signal). Instead this harness runs the SAME r1 machinery — SparseGRPOTrainer,
bucket packing, de-padding, group advantages — on a synthetic arithmetic
corpus with a SHAPED reward a from-scratch policy can climb within ~30
updates:

    reward = digit_density                  (fraction of response tokens that
                                             are digits — dense signal from
                                             the first rollout)
           + 0.5 · has_boxed_format         (emits `\\boxed{...}`)
           + 1.0 · boxed_answer_correct     (grader-verified exact answer)
           + 0.25 · stopped_with_eos

The committed artifact is the metrics series (objective/scores rising), the
repo's answer to the reference's reward-curve evidence at a scale the
hardware budget allows. Run on the TPU (default env) or CPU
(`PYTHONPATH= JAX_PLATFORMS=cpu LEARN_MODEL=tiny`).

Env knobs: LEARN_UPDATES (30), LEARN_MODEL (small8m | tiny), LEARN_PROMPTS
(32 per update), LEARN_RESPONSE (64), LEARN_LR (1e-2), LEARN_OUT
(docs/artifacts). LR note: from-scratch models need orders more than the
fine-tuning 6e-6, but too hot COLLAPSES the policy — identical samples →
zero group advantages → the sparse filter skips the update. Measured on
CPU: tiny (0.1M) wants 2e-2 (3e-4 is flat noise); small8m (2.9M) at 2e-2
collapses (33/40 updates skipped), at 8e-3 climbs cleanly 0.15 → 0.66
over 40 updates with zero skips. Default 8e-3.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def model_config(name: str):
    from nanorlhf_tpu.core import ModelConfig

    if name == "tiny":
        return ModelConfig.qwen2_tiny(vocab_size=512)
    # ~4M-param decoder: an order beyond the 336k-param toy of
    # tests/test_learning.py, small enough that ~40 updates fit a tunnel
    # session (or ~20 min of single-core CPU). Vocab stays 512: the toy
    # tokenizer's digit-token share sets the reward's base rate, and at
    # 4096 the digit density is so low that most GRPO groups score
    # identically zero and the sparse filter skips the update.
    return dataclasses.replace(
        ModelConfig.qwen2_tiny(vocab_size=512),
        hidden_size=256,
        intermediate_size=688,
        num_hidden_layers=4,
        num_attention_heads=8,
        num_key_value_heads=2,
    )


_BOXED = re.compile(r"\\boxed\{([^{}]*)\}")


def make_reward(answers_by_prompt: dict):
    """Shaped r1-style reward (see module docstring). `answers_by_prompt`
    maps the prompt text (sans padding) to the ground-truth answer string."""

    def reward(pmt_and_responses, eos_token):
        out = []
        for s in pmt_and_responses:
            # split prompt/response at the generation marker the toy chat
            # template ends with; fall back to scoring the whole string
            resp = s.split("<assistant>")[-1]
            toks = resp.replace(eos_token, " ").split()
            digits = sum(1 for t in toks if t.strip().isdigit())
            r = digits / max(len(toks), 1)
            m = _BOXED.search(resp)
            if m:
                r += 0.5
                want = None
                for p, a in answers_by_prompt.items():
                    if p in s:
                        want = a
                        break
                if want is not None and m.group(1).strip() == want:
                    r += 1.0
            if eos_token in s:
                r += 0.25
            out.append(r)
        return np.asarray(out, np.float32)

    return reward


def build_corpus(tok, n: int, seed: int):
    """Arithmetic prompts through the toy chat template + their answers."""
    rng = np.random.default_rng(seed)
    texts, answers = [], {}
    for _ in range(n):
        a, b = int(rng.integers(1, 50)), int(rng.integers(1, 50))
        q = f"What is {a} plus {b}? Put the answer in \\boxed{{}}."
        texts.append(q)
        answers[q] = str(a + b)
    return texts, answers


def main():
    import jax
    import jax.numpy as jnp

    from nanorlhf_tpu.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()  # warm-start repeat sessions (VERDICT r4 #2)

    from nanorlhf_tpu.core import init_params
    from nanorlhf_tpu.data import ToyTokenizer, PromptDataset
    from nanorlhf_tpu.data.datasets import encode_texts, _left_pad
    from nanorlhf_tpu.parallel import MeshConfig
    from nanorlhf_tpu.trainer import AlgoName, RLConfig
    from nanorlhf_tpu.trainer.sparse_grpo import SparseGRPOTrainer

    updates = int(os.environ.get("LEARN_UPDATES", 30))
    model = os.environ.get("LEARN_MODEL", "small8m")
    prompts = int(os.environ.get("LEARN_PROMPTS", 32))
    resp = int(os.environ.get("LEARN_RESPONSE", 64))
    out_dir = os.environ.get("LEARN_OUT", "docs/artifacts")

    mcfg = model_config(model)
    tok = ToyTokenizer(vocab_size=min(4096, mcfg.vocab_size))
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.bfloat16)
    n_params = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))

    texts, answers = build_corpus(tok, 256, seed=0)
    templated = [
        tok.apply_chat_template([{"role": "user", "content": t}],
                                tokenize=False, add_generation_prompt=True)
        for t in texts
    ]
    ids = encode_texts(tok, templated, max_prompt_len=32)
    dataset = PromptDataset(_left_pad(ids, tok.pad_token_id), tok.pad_token_id)

    # pid-unique fresh run dir: the metrics logger APPENDS, and a fixed
    # path would let a concurrent or stale invocation pollute the committed
    # artifact (observed: two overlapped runs interleaved one jsonl)
    import shutil

    run_dir = f"/tmp/nanorlhf_learning_run.{os.getpid()}"
    shutil.rmtree(run_dir, ignore_errors=True)
    cfg = RLConfig(
        algo=AlgoName.GRPO,
        exp_name="learning-curve",
        output_dir=run_dir,
        response_length=resp,
        temperature=1.0,
        top_p=0.95,
        rollout_top_k=0,                 # r1 default: exact nucleus
        sample_n=4,
        kl_coef=0.0,                     # r1: no KL (`grpo_r1.py:138`)
        learning_rate=float(os.environ.get("LEARN_LR", 8e-3)),
        # LEARN_PROMPTS is the GLOBAL prompts-per-update; the mesh takes
        # every visible device on its data axis (1 on the single-chip
        # tunnel, 8 on the virtual CPU test mesh)
        per_device_train_batch_size=max(1, prompts // len(jax.devices())),
        gradient_accumulation_steps=1,
        num_mini_batches=1,
        total_episodes=updates
        * max(1, prompts // len(jax.devices())) * len(jax.devices()) * 4,
        use_lora=False,                  # full FT: random init has no base
        gradient_checkpointing=True,
        mesh=MeshConfig(-1, 1, 1),
        save_steps=0,
        report_to="jsonl",
        logging_steps=1,
    )
    trainer = SparseGRPOTrainer(cfg, mcfg, tok, params, dataset,
                                make_reward(answers))
    state = trainer.train(num_updates=updates)

    rows = [json.loads(l) for l in open(os.path.join(run_dir, "metrics.jsonl"))]
    series = [
        {
            "step": r["step"],
            "score": round(r.get("eval_objective/scores_old", 0.0), 4),
            "entropy": round(r.get("objective/entropy_old", 0.0), 3),
            # response-length growth — the reference's len.png evidence
            "resp_len": round(r.get("eval_response_length", 0.0), 2),
        }
        for r in rows
        if "eval_objective/scores_old" in r
    ]
    os.makedirs(out_dir, exist_ok=True)
    first = np.mean([s["score"] for s in series[:3]]) if series else 0.0
    last = np.mean([s["score"] for s in series[-3:]]) if series else 0.0
    artifact = {
        "what": "sparse-GRPO (r1 path) reward curve, shaped math-format "
                "reward, from-scratch policy",
        "backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "model": model,
        "n_params": n_params,
        "updates": state["global_step"],
        "episodes": state["episode"],
        "reward_first3_avg": round(float(first), 4),
        "reward_last3_avg": round(float(last), 4),
        "series": series,
    }
    path = os.path.join(out_dir, "learning_curve_r4.json")
    with open(path, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"\nwrote {path}: reward {first:.3f} -> {last:.3f} over "
          f"{state['global_step']} updates ({n_params/1e6:.1f}M params, "
          f"{jax.default_backend()})")


if __name__ == "__main__":
    main()
