"""Silicon learning-curve artifact: sparse GRPO (the r1-zero path) climbing a
shaped math-format reward from scratch.

The reference's learning evidence is a rising reward curve
(`/root/reference/README.md:36-37`, `docs/perf.png`) and MATH-500 accuracy
improving from a base model (`examples/r1-v0/README.md:9-14`). This
environment has zero egress and no pretrained checkpoint on disk, so a binary
boxed-answer reward on a random-init policy would be flat (no gradient
signal). Instead this harness runs the SAME r1 machinery — SparseGRPOTrainer,
bucket packing, de-padding, group advantages — on a synthetic arithmetic
corpus with a SHAPED reward a from-scratch policy can climb within ~30
updates:

    reward = digit_density                  (fraction of response tokens that
                                             are digits — dense signal from
                                             the first rollout)
           + 0.5 · has_boxed_format         (emits `\\boxed{...}`)
           + 1.0 · boxed_answer_correct     (grader-verified exact answer)
           + 0.25 · stopped_with_eos

The committed artifact is the metrics series (objective/scores rising), the
repo's answer to the reference's reward-curve evidence at a scale the
hardware budget allows. Run on the TPU (default env) or CPU
(`PYTHONPATH= JAX_PLATFORMS=cpu LEARN_MODEL=tiny`).

A second phase (`LEARN_BINARY_UPDATES > 0`) then SWAPS the reward to the
r1-style BINARY one — 1.0 iff the boxed answer is exactly right, else 0.0,
nothing in between (`examples/r1-v0/grpo_r1.py` reward contract) — and
keeps training the same policy. This is the regime the reference's 1.5B
evidence lives in: most GRPO groups score identically (all-wrong or
all-right) and carry zero advantage, so the sparse filter starves; the
phase records skip counts and whether binary accuracy still climbs from
the shaped-phase policy. A from-scratch policy straight into binary would
be flat forever (never emits \\boxed), which is why the shaped phase runs
first — the curriculum makes the binary regime reachable on this
hardware budget.

Env knobs: LEARN_UPDATES (30), LEARN_BINARY_UPDATES (0), LEARN_MODEL
(small8m | tiny | 1_5b), LEARN_PROMPTS (32 per update), LEARN_RESPONSE
(64), LEARN_LR (8e-3), LEARN_TEMP (1.0 — hotter keeps exploration alive
past the format plateau; the entropy collapse at 8e-3/1.0 freezes the
policy before it ever answers correctly), LEARN_OUT (docs/artifacts). LR note: from-scratch models
need orders more than the fine-tuning 6e-6, but too hot COLLAPSES the
policy — identical samples → zero group advantages → the sparse filter
skips the update. Measured on CPU: tiny (0.1M) wants 2e-2 (3e-4 is flat
noise); small8m (2.9M) at 2e-2 collapses (33/40 updates skipped), at 8e-3
climbs cleanly 0.15 → 0.66 over 40 updates with zero skips. Default 8e-3.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def model_config(name: str):
    from nanorlhf_tpu.core import ModelConfig

    if name == "tiny":
        return ModelConfig.qwen2_tiny(vocab_size=512)
    if name == "1_5b":
        # flagship GEOMETRY (hidden/layers/heads of Qwen2-1.5B) at the toy
        # 512 vocab — the silicon learning-curve shape. Vocab must stay 512:
        # the digit-token share of the toy tokenizer sets the reward's base
        # rate, and at real-vocab sizes the from-scratch digit density is so
        # low every group ties at zero and the sparse filter starves.
        return dataclasses.replace(ModelConfig.qwen2_1_5b(), vocab_size=512)
    # ~4M-param decoder: an order beyond the 336k-param toy of
    # tests/test_learning.py, small enough that ~40 updates fit a tunnel
    # session (or ~20 min of single-core CPU). Vocab stays 512: the toy
    # tokenizer's digit-token share sets the reward's base rate, and at
    # 4096 the digit density is so low that most GRPO groups score
    # identically zero and the sparse filter skips the update.
    return dataclasses.replace(
        ModelConfig.qwen2_tiny(vocab_size=512),
        hidden_size=256,
        intermediate_size=688,
        num_hidden_layers=4,
        num_attention_heads=8,
        num_key_value_heads=2,
    )


_BOXED = re.compile(r"\\boxed\{([^{}]*)\}")


def _expected_answer(s: str, answers_by_prompt: dict):
    """Ground truth for the prompt embedded in decoded sample `s` (first
    prompt-substring match wins) — the ONE matching rule both rewards share,
    so decode-round-trip edge fixes can't diverge the two phases."""
    for p, a in answers_by_prompt.items():
        if p in s:
            return a
    return None


def make_reward(answers_by_prompt: dict):
    """Shaped r1-style reward (see module docstring). `answers_by_prompt`
    maps the prompt text (sans padding) to the ground-truth answer string."""

    def reward(pmt_and_responses, eos_token):
        out = []
        for s in pmt_and_responses:
            # split prompt/response at the generation marker the toy chat
            # template ends with; fall back to scoring the whole string
            resp = s.split("<assistant>")[-1]
            toks = resp.replace(eos_token, " ").split()
            digits = sum(1 for t in toks if t.strip().isdigit())
            r = digits / max(len(toks), 1)
            m = _BOXED.search(resp)
            if m:
                r += 0.5
                want = _expected_answer(s, answers_by_prompt)
                if want is not None and m.group(1).strip() == want:
                    r += 1.0
            if eos_token in s:
                r += 0.25
            out.append(r)
        return np.asarray(out, np.float32)

    return reward


def make_binary_reward(answers_by_prompt: dict):
    """r1-contract binary reward: 1.0 iff the \\boxed answer is exactly the
    ground truth, else 0.0 — no format shaping, no partial credit. The
    sparse-filter starvation regime (all-same groups carry zero advantage)."""

    def reward(pmt_and_responses, eos_token):
        out = []
        for s in pmt_and_responses:
            m = _BOXED.search(s.split("<assistant>")[-1])
            want = _expected_answer(s, answers_by_prompt) if m else None
            out.append(1.0 if (want is not None
                               and m.group(1).strip() == want) else 0.0)
        return np.asarray(out, np.float32)

    return reward


def build_corpus(tok, n: int, seed: int, max_operand: int = 50):
    """Arithmetic prompts through the toy chat template + their answers.
    Addends are drawn from 1..max_operand-1 (EXCLUSIVE upper bound,
    LEARN_MAX_OPERAND; floored at 2 so the range is never empty): small
    operands make answers single tokens, so from-scratch exploration can
    actually hit correctness — the knob that decides whether the binary
    phase has any signal to find."""
    rng = np.random.default_rng(seed)
    max_operand = max(2, max_operand)
    texts, answers = [], {}
    for _ in range(n):
        a = int(rng.integers(1, max_operand))
        b = int(rng.integers(1, max_operand))
        q = f"What is {a} plus {b}? Put the answer in \\boxed{{}}."
        texts.append(q)
        answers[q] = str(a + b)
    return texts, answers


def main():
    import signal

    # the silicon session bounds this run with coreutils `timeout` (SIGTERM)
    # — convert it to an exception so the artifact still gets written from
    # whatever updates completed (a killed run losing its whole curve is the
    # worst outcome on a flaky tunnel). Installed BEFORE the compile-cache
    # claim so its SIGTERM chain defers to this one; its sentinel is then
    # cleaned by atexit on the resulting clean exit.
    def _on_term(signum, frame):
        raise KeyboardInterrupt("SIGTERM")

    signal.signal(signal.SIGTERM, _on_term)

    import jax
    import jax.numpy as jnp

    from nanorlhf_tpu.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()  # warm-start repeat sessions (VERDICT r4 #2)

    from nanorlhf_tpu.core import init_params
    from nanorlhf_tpu.data import ToyTokenizer, PromptDataset
    from nanorlhf_tpu.data.datasets import encode_texts, _left_pad
    from nanorlhf_tpu.parallel import MeshConfig
    from nanorlhf_tpu.trainer import AlgoName, RLConfig
    from nanorlhf_tpu.trainer.sparse_grpo import SparseGRPOTrainer

    updates = int(os.environ.get("LEARN_UPDATES", 30))
    binary_updates = int(os.environ.get("LEARN_BINARY_UPDATES", 0))
    model = os.environ.get("LEARN_MODEL", "small8m")
    prompts = int(os.environ.get("LEARN_PROMPTS", 32))
    resp = int(os.environ.get("LEARN_RESPONSE", 64))
    out_dir = os.environ.get("LEARN_OUT", "docs/artifacts")

    mcfg = model_config(model)
    tok = ToyTokenizer(vocab_size=min(4096, mcfg.vocab_size))
    params = init_params(mcfg, jax.random.PRNGKey(0), jnp.bfloat16)
    n_params = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))

    texts, answers = build_corpus(
        tok, 256, seed=0,
        max_operand=int(os.environ.get("LEARN_MAX_OPERAND", 50)),
    )
    templated = [
        tok.apply_chat_template([{"role": "user", "content": t}],
                                tokenize=False, add_generation_prompt=True)
        for t in texts
    ]
    ids = encode_texts(tok, templated, max_prompt_len=32)
    dataset = PromptDataset(_left_pad(ids, tok.pad_token_id), tok.pad_token_id)

    # pid-unique fresh run dir: the metrics logger APPENDS, and a fixed
    # path would let a concurrent or stale invocation pollute the committed
    # artifact (observed: two overlapped runs interleaved one jsonl)
    import shutil

    run_dir = f"/tmp/nanorlhf_learning_run.{os.getpid()}"
    shutil.rmtree(run_dir, ignore_errors=True)
    cfg = RLConfig(
        algo=AlgoName.GRPO,
        exp_name="learning-curve",
        output_dir=run_dir,
        response_length=resp,
        temperature=float(os.environ.get("LEARN_TEMP", 1.0)),
        top_p=0.95,
        rollout_top_k=0,                 # r1 default: exact nucleus
        sample_n=4,
        kl_coef=0.0,                     # r1: no KL (`grpo_r1.py:138`)
        learning_rate=float(os.environ.get("LEARN_LR", 8e-3)),
        # LEARN_PROMPTS is the GLOBAL prompts-per-update; the mesh takes
        # every visible device on its data axis (1 on the single-chip
        # tunnel, 8 on the virtual CPU test mesh)
        per_device_train_batch_size=max(1, prompts // len(jax.devices())),
        gradient_accumulation_steps=1,
        num_mini_batches=1,
        total_episodes=(updates + binary_updates)
        * max(1, prompts // len(jax.devices())) * len(jax.devices()) * 4,
        use_lora=False,                  # full FT: random init has no base
        gradient_checkpointing=True,
        mesh=MeshConfig(-1, 1, 1),
        save_steps=0,
        report_to="jsonl",
        logging_steps=1,
    )
    trainer = SparseGRPOTrainer(cfg, mcfg, tok, params, dataset,
                                make_reward(answers))
    interrupted = None
    shaped_steps = None
    shaped_skips = 0
    binary_stats = None
    try:
        state = trainer.train(num_updates=updates)
        shaped_steps = state["global_step"]
        shaped_skips = state["rollouts"] - shaped_steps
        if binary_updates > 0:
            # PHASE 2: same policy, same trainer — only the reward becomes
            # the r1 binary contract. The sparse filter now sees all-same
            # groups (zero advantage) whenever a prompt is uniformly
            # failed/solved; skipped updates consume a rollout without
            # stepping, which is exactly the starvation the 1.5B regime
            # exhibits.
            trainer.reward_func = make_binary_reward(answers)
            state = trainer.train(num_updates=binary_updates)
    except KeyboardInterrupt as e:
        interrupted = str(e) or "interrupted"
        state = trainer.state
        print(f"\n[learning_run] interrupted ({interrupted}) — writing the "
              f"artifact from {state['global_step']} completed updates")
        if shaped_steps is None:  # died in phase 1
            shaped_steps = state["global_step"]
            shaped_skips = state["rollouts"] - shaped_steps
            binary_updates = 0
    shaped_rollouts = shaped_steps + shaped_skips
    if binary_updates > 0:
        # derive ATTEMPTED from the rollout counter, not the env knob — an
        # interrupt mid-phase-2 would otherwise record attempts that never
        # ran, making the committed skip-rate internally inconsistent
        binary_attempted = state["rollouts"] - shaped_rollouts
        binary_stats = {
            "updates_attempted": binary_attempted,
            "updates_stepped": state["global_step"] - shaped_steps,
            "updates_skipped_by_sparse_filter": (
                (state["rollouts"] - state["global_step"]) - shaped_skips
            ),
        }

    # tolerate a torn trailing line: the SIGTERM→KeyboardInterrupt can land
    # inside the logger's write, and the recovery path must not lose the
    # whole curve to one malformed row
    rows = []
    for line in open(os.path.join(run_dir, "metrics.jsonl")):
        try:
            rows.append(json.loads(line))
        except json.JSONDecodeError:
            continue
    series = [
        {
            "step": r["step"],
            "score": round(r.get("eval_objective/scores_old", 0.0), 4),
            "entropy": round(r.get("policy/entropy_avg_new", 0.0), 3),
            # response-length growth — the reference's len.png evidence
            "resp_len": round(r.get("eval_response_length", 0.0), 2),
            # steps logged after the swap carry the binary phase marker
            "phase": "binary" if r["step"] > shaped_steps else "shaped",
        }
        for r in rows
        if "eval_objective/scores_old" in r
    ]
    os.makedirs(out_dir, exist_ok=True)
    shaped_series = [s for s in series if s["phase"] == "shaped"]
    bin_series = [s for s in series if s["phase"] == "binary"]
    # skip rows (sparse_skip/*, logged by the trainer when every group ties):
    # raw_score_mean distinguishes starved-at-zero (uniformly failed) from
    # starved-solved (uniformly correct) — both carry zero group advantage
    skip_raw = [
        {"rollout": r["sparse_skip/rollout_index"],
         "raw_score_mean": round(r["sparse_skip/raw_score_mean"], 4)}
        for r in rows if "sparse_skip/raw_score_mean" in r
    ]
    # rollout_index is the 1-based CONSUMED count (RolloutStream sets
    # rollouts = index + 1), so the last shaped-phase skip carries exactly
    # shaped_rollouts — strictly-greater keeps its shaped-scale score out
    # of the binary average
    bin_skip_raw = [s for s in skip_raw if s["rollout"] > shaped_rollouts]
    first = np.mean([s["score"] for s in shaped_series[:3]]) if shaped_series else 0.0
    last = np.mean([s["score"] for s in shaped_series[-3:]]) if shaped_series else 0.0
    artifact = {
        "what": "sparse-GRPO (r1 path) reward curve, shaped math-format "
                "reward, from-scratch policy"
                + (" + binary-reward phase (r1 contract)" if binary_stats
                   else ""),
        "backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "model": model,
        "n_params": n_params,
        "updates": state["global_step"],
        "episodes": state["episode"],
        "reward_first3_avg": round(float(first), 4),
        "reward_last3_avg": round(float(last), 4),
        "series": series,
    }
    if binary_stats:
        b_first = np.mean([s["score"] for s in bin_series[:3]]) if bin_series else 0.0
        b_last = np.mean([s["score"] for s in bin_series[-3:]]) if bin_series else 0.0
        binary_stats["binary_first3_avg"] = round(float(b_first), 4)
        binary_stats["binary_last3_avg"] = round(float(b_last), 4)
        if bin_skip_raw:
            means = [s["raw_score_mean"] for s in bin_skip_raw]
            avg = float(np.mean(means))
            binary_stats["skipped_raw_score_mean_avg"] = round(avg, 4)
            # a skipped batch only guarantees PER-GROUP ties, not batch
            # uniformity — a mid-range mean is some groups all-solved and
            # others all-failed, its own regime
            binary_stats["starvation_mode"] = (
                "uniformly_failed" if avg < 0.05
                else "uniformly_solved" if avg > 0.95
                else "mixed_groups"
            )
        artifact["binary_phase"] = binary_stats
    if interrupted:
        artifact["interrupted"] = interrupted
    path = os.path.join(out_dir, "learning_curve_r5.json")
    with open(path, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"\nwrote {path}: shaped reward {first:.3f} -> {last:.3f} over "
          f"{shaped_steps} updates ({n_params/1e6:.1f}M params, "
          f"{jax.default_backend()})"
          + (f"; binary phase {binary_stats}" if binary_stats else ""))


if __name__ == "__main__":
    main()
