#!/usr/bin/env python
"""nanolint — project-invariant static analysis for nanorlhf_tpu.

Usage:
    python tools/nanolint.py [paths...] [--baseline FILE]
                             [--write-baseline REASON] [--lock-graph]
                             [--json] [--rules PREFIX[,PREFIX...]]

Default paths: nanorlhf_tpu/ tools/. Exit status 0 iff every finding is
either allowlisted in source (`# nanolint: allow[rule] reason`) or
present in the baseline file with a written reason, AND no baseline
entry is stale. See docs/STATIC_ANALYSIS.md for the rule catalog and
the fix-or-suppress workflow.

Runs jax-free: the engine imports only stdlib plus the telemetry
exporter's Prometheus validator.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from nanorlhf_tpu.analysis import (  # noqa: E402
    determinism, engine, jitpurity, lockgraph, registry)

DEFAULT_BASELINE = REPO / "nanorlhf_tpu" / "analysis" / "baseline.json"

RULE_FAMILIES = {
    "determinism": determinism.run,
    "jit": jitpurity.run,
    "registry": registry.run,
    "lockorder": lockgraph.run,
}


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: nanorlhf_tpu/ tools/)")
    ap.add_argument("--baseline", default=str(DEFAULT_BASELINE),
                    help="baseline JSON (default: %(default)s)")
    ap.add_argument("--write-baseline", metavar="REASON", default=None,
                    help="write all current findings to the baseline file "
                         "with REASON and exit 0")
    ap.add_argument("--lock-graph", action="store_true",
                    help="print the extracted lock graph and exit")
    ap.add_argument("--json", action="store_true", help="machine output")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule-family filter "
                         "(determinism,jit,registry,lockorder)")
    args = ap.parse_args(argv)

    targets = ([Path(p).resolve() for p in args.paths] if args.paths
               else [REPO / "nanorlhf_tpu", REPO / "tools"])
    # never lint the test fixtures dir (it contains deliberately-bad code)
    targets = [t for t in targets if t.exists()]
    proj = engine.load_project(REPO, targets)
    proj.files = [f for f in proj.files
                  if "/fixtures/" not in f.relpath
                  and not f.relpath.startswith("tests/")]

    if args.lock_graph:
        graph = lockgraph.extract(proj)
        print(lockgraph.render(graph))
        return 0

    families = (args.rules.split(",") if args.rules
                else list(RULE_FAMILIES))
    findings: list[engine.Finding] = engine.parse_errors(proj)
    for fam in families:
        findings.extend(RULE_FAMILIES[fam](proj))
    findings = engine.apply_allowlist(proj, findings)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))

    baseline_path = Path(args.baseline)
    if not baseline_path.is_absolute():
        baseline_path = (REPO / baseline_path).resolve()
        if not baseline_path.exists() and DEFAULT_BASELINE.exists():
            # tolerate the documented shorthand `--baseline analysis/baseline.json`
            alt = REPO / "nanorlhf_tpu" / args.baseline
            baseline_path = alt if alt.exists() else baseline_path

    if args.write_baseline is not None:
        engine.write_baseline(baseline_path, findings, args.write_baseline)
        print(f"wrote {len(findings)} finding(s) to {baseline_path}")
        return 0

    entries, reason_errors = engine.load_baseline(baseline_path)
    new, stale = engine.diff_baseline(findings, entries)

    if args.json:
        print(json.dumps({
            "findings": len(findings), "new": [f.__dict__ for f in new],
            "stale": stale, "baseline_errors": reason_errors}, indent=2))
    else:
        for f in new:
            print(f.render())
        for e in stale:
            print(f"{baseline_path.name}: stale baseline entry "
                  f"{e['rule']}::{e['path']}::{e['detail']} — the finding "
                  f"no longer fires; delete the entry")
        for err in reason_errors:
            print(f"{baseline_path.name}: {err}")
        n_ok = len(findings) - len(new)
        print(f"nanolint: {len(findings)} finding(s), {n_ok} baselined/"
              f"known, {len(new)} new, {len(stale)} stale baseline "
              f"entr{'y' if len(stale) == 1 else 'ies'}")

    return 1 if (new or stale or reason_errors) else 0


if __name__ == "__main__":
    raise SystemExit(main())
