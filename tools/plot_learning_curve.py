"""Render docs/artifacts/learning_curve_r5.json as a PNG — the repo's
analogue of the reference's reward-curve evidence (`docs/perf.png`,
`examples/r1-v0/len.png`).

Chart method (dataviz): change-over-time → line chart; one y-axis per
panel (score and response length are different measures → two stacked
panels, never dual-axis); categorical hues by phase identity in fixed
slot order (slot 1 blue = shaped, slot 2 orange = binary — the validated
reference palette's adjacent pair, worst CVD ΔE 9.1 / normal 19.6 on the
light surface per its documentation; no JS runtime in this image to
re-run the validator, so the documented-validated values are used
verbatim); 2px lines, recessive grid, direct phase labels + legend,
text in ink tokens not series colors.

Usage: python tools/plot_learning_curve.py [artifact.json] [out.png]
(no jax; matplotlib + stdlib only)
"""

import json
import sys

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402

SURFACE = "#fcfcfb"
INK = "#0b0b0b"
INK2 = "#52514e"
GRID = "#e5e4e0"
SHAPED = "#2a78d6"  # categorical slot 1 (blue)
BINARY = "#eb6834"  # categorical slot 2 (orange)


def main():
    src = sys.argv[1] if len(sys.argv) > 1 else "docs/artifacts/learning_curve_r5.json"
    out = sys.argv[2] if len(sys.argv) > 2 else "docs/artifacts/learning_curve_r5.png"
    a = json.load(open(src))
    series = a["series"]
    shaped = [s for s in series if s.get("phase", "shaped") == "shaped"]
    binary = [s for s in series if s.get("phase") == "binary"]

    fig, (ax1, ax2) = plt.subplots(
        2, 1, figsize=(8.4, 5.6), sharex=True,
        gridspec_kw={"height_ratios": [2.1, 1]},
    )
    fig.patch.set_facecolor(SURFACE)

    for ax in (ax1, ax2):
        ax.set_facecolor(SURFACE)
        ax.grid(True, color=GRID, linewidth=0.8, zorder=0)
        for side in ("top", "right"):
            ax.spines[side].set_visible(False)
        for side in ("left", "bottom"):
            ax.spines[side].set_color(INK2)
        ax.tick_params(colors=INK2, labelsize=9)

    ax1.plot([s["step"] for s in shaped], [s["score"] for s in shaped],
             color=SHAPED, linewidth=2, zorder=3, label="shaped reward")
    if binary:
        ax1.plot([s["step"] for s in binary], [s["score"] for s in binary],
                 color=BINARY, linewidth=2, zorder=3,
                 label="binary reward (r1 contract)")
    bp = a.get("binary_phase")
    boundary = max((s["step"] for s in shaped), default=0)
    if bp:
        ax1.axvline(boundary + 0.5, color=INK2, linewidth=1,
                    linestyle=(0, (4, 3)), zorder=2)
        note = (f"binary phase: {bp['updates_stepped']} stepped / "
                f"{bp['updates_skipped_by_sparse_filter']} skipped\n"
                "(sparse filter: all-same groups carry zero advantage)")
        ax1.annotate(note, xy=(boundary + 1, 0.04),
                     xycoords=("data", "axes fraction"),
                     fontsize=8.5, color=INK2, va="bottom")
    # direct label on the shaped series end (selective, not every point)
    if shaped:
        last = shaped[-1]
        ax1.annotate(f"{last['score']:.2f}", xy=(last["step"], last["score"]),
                     xytext=(4, 2), textcoords="offset points",
                     fontsize=9, color=INK, fontweight="bold")
    ax1.set_ylabel("mean rollout score", color=INK, fontsize=10)
    if binary:  # one series needs no legend box — the title names it
        ax1.legend(loc="upper left", frameon=False, fontsize=9,
                   labelcolor=INK2)
    n_m = a["n_params"] / 1e6
    ax1.set_title(
        f"sparse GRPO (r1 path), from-scratch {n_m:.1f}M policy — "
        f"{a['backend']} ({a['device_kind']})",
        color=INK, fontsize=11, loc="left", pad=10,
    )

    # phase colors must match the top panel's encoding (color follows the
    # entity — here the training regime — in both panels)
    ax2.plot([s["step"] for s in shaped], [s["resp_len"] for s in shaped],
             color=SHAPED, linewidth=2, zorder=3)
    if binary:
        ax2.plot([s["step"] for s in binary], [s["resp_len"] for s in binary],
                 color=BINARY, linewidth=2, zorder=3)
    if bp:
        ax2.axvline(boundary + 0.5, color=INK2, linewidth=1,
                    linestyle=(0, (4, 3)), zorder=2)
    ax2.set_ylabel("response len (tok)", color=INK, fontsize=10)
    ax2.set_xlabel("update", color=INK, fontsize=10)

    fig.tight_layout()
    fig.savefig(out, dpi=160, facecolor=SURFACE)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
