#!/bin/bash
# One-command silicon session (VERDICT r3 #1): run the moment the axon
# tunnel is up. Each step is ONE jax process (single TPU claim); steps run
# sequentially with a socket preflight in between so a dead relay skips
# cleanly instead of hanging a claim. Outputs land in $OUT (default
# /tmp/silicon_r5/).
#
#   bash tools/silicon_session.sh            # full session
#   STEPS=bench bash tools/silicon_session.sh
set -u
cd "$(dirname "$0")/.."
OUT="${OUT:-/tmp/silicon_r5}"
mkdir -p "$OUT"
STEPS="${STEPS:-ablate bench learn drift}"

alive() {
  python3 tools/tunnel_alive.py  # single source of truth for relay ports
}

run_step() {  # name, timeout_s, command...
  local name=$1 tmo=$2; shift 2
  if ! alive; then
    echo "[$name] tunnel DOWN — skipping" | tee -a "$OUT/session.log"
    return 1
  fi
  echo "[$name] start $(date +%H:%M:%S)" | tee -a "$OUT/session.log"
  timeout "$tmo" "$@" > "$OUT/$name.log" 2>&1
  local rc=$?
  echo "[$name] rc=$rc $(date +%H:%M:%S)" | tee -a "$OUT/session.log"
  tail -3 "$OUT/$name.log"
  return $rc
}

for s in $STEPS; do
  case $s in
    ablate) run_step ablate 2400 python tools/ablate_decode.py ;;
    bench)  run_step bench 4800 env BENCH_ATTEMPT_TIMEOUT=4300 python bench.py ;;
    learn)  run_step learn 3600 env LEARN_MODEL=1_5b LEARN_UPDATES=25 \
                LEARN_BINARY_UPDATES=15 python tools/learning_run.py ;;
    drift)  run_step drift 1800 python tools/capture_drift.py ;;
  esac
done
echo "session done; logs in $OUT"
