"""Axon relay liveness probe — THE one place the relay port set lives.

Exit 0 when any relay port accepts a TCP connection, 1 otherwise. Plain
sockets only: a jax probe against a dead relay hangs ~40 min and can wedge
the tunnel. Used by tools/silicon_session.sh, tools/tunnel_watch.sh, and
bench.py (which imports RELAY_PORTS).
"""

import socket
import sys

RELAY_PORTS = (8082, 8092, 8102, 8112)


def alive(timeout: float = 3.0) -> bool:
    for port in RELAY_PORTS:
        s = socket.socket()
        s.settimeout(timeout)
        try:
            s.connect(("127.0.0.1", port))
            return True
        except OSError:
            pass
        finally:
            s.close()
    return False


if __name__ == "__main__":
    sys.exit(0 if alive() else 1)
