#!/bin/bash
# Poll the axon relay ports; the moment one accepts, fire the full silicon
# session (ablate -> bench -> learn -> drift). Designed to run in the
# background for an entire round: plain-socket probes only (a jax probe on a
# dead relay hangs ~40 min and can wedge the tunnel).
#
#   bash tools/tunnel_watch.sh   # blocks until the tunnel appears, runs once
set -u
cd "$(dirname "$0")/.."
OUT="${OUT:-/tmp/silicon_r5}"
mkdir -p "$OUT"
POLL="${POLL:-20}"

alive() {
  python3 tools/tunnel_alive.py  # single source of truth for relay ports
}

echo "watch start $(date +%H:%M:%S), polling every ${POLL}s" >> "$OUT/watch.log"
n=0
while ! alive; do
  sleep "$POLL"
  n=$((n + 1))
  if [ $((n % 30)) -eq 0 ]; then
    echo "still down after $((n * POLL))s $(date +%H:%M:%S)" >> "$OUT/watch.log"
  fi
done
echo "tunnel UP $(date +%H:%M:%S) — settling 20s then starting session" >> "$OUT/watch.log"
sleep 20
OUT="$OUT" bash tools/silicon_session.sh >> "$OUT/watch.log" 2>&1
echo "session complete $(date +%H:%M:%S)" >> "$OUT/watch.log"
